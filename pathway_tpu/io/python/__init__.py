"""Python connector: ``pw.io.python.read`` + ``ConnectorSubject``.

Mirrors the reference's ``python/pathway/io/python/__init__.py:47``
(``ConnectorSubject``: a user thread pushing rows through a queue into the engine —
Rust side ``PythonReader`` at ``src/connectors/data_storage.rs:927``). Here the
subject pushes directly into a ``StreamInputNode``; the run loop stamps whatever
arrived between autocommit ticks with the next logical time.
"""

from __future__ import annotations

import json as _json
import threading
from typing import Any

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.keys import row_keys, sequential_keys
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


class ConnectorSubject:
    """Subclass and implement ``run()`` calling ``self.next(**kwargs)``."""

    def __init__(self, datasource_name: str = "python"):
        self._node: ops.StreamInputNode | None = None
        self._columns: list[str] = []
        self._pk_cols: list[str] | None = None
        self._seq = 0
        self._closed = False
        self._started = threading.Event()

    # ---- user API ----
    def run(self) -> None:
        raise NotImplementedError

    def next(self, **kwargs: Any) -> None:
        values = tuple(kwargs.get(c) for c in self._columns)
        self._push(values, diff=1)

    def next_json(self, data: dict) -> None:
        self.next(**data)

    def next_batch(self, rows: list[dict]) -> None:
        """Microbatch ingestion: one lock acquisition and vectorized key
        generation for a whole block of rows — the block-first counterpart of
        per-row ``next`` (which hashes and locks per event). Keys are
        bit-identical to calling ``next`` row by row."""
        if not rows:
            return
        values = [tuple(r.get(c) for c in self._columns) for r in rows]
        keys = self._keys_for(values)
        assert self._node is not None, "subject not attached to a running graph"
        self._node.push_many(
            (int(k), v, 1) for k, v in zip(keys, values)
        )

    def next_str(self, line: str) -> None:
        self.next(data=line)

    def next_bytes(self, data: bytes) -> None:
        self.next(data=data)

    def commit(self) -> None:
        pass  # ticks auto-commit; kept for API parity

    def close(self) -> None:
        self._closed = True

    def on_stop(self) -> None:
        pass

    @property
    def _session_type(self) -> str:
        return "native"

    # ---- internals ----
    def _keys_for(self, values: list[tuple]) -> np.ndarray:
        """Row keys for a block of value tuples — the single source of the
        key-derivation recipe, shared by per-row ``next``/``_remove`` (n=1)
        and ``next_batch`` so the two stay bit-identical by construction."""
        n = len(values)
        if self._pk_cols:
            idx = [self._columns.index(c) for c in self._pk_cols]
            arrs = []
            for i in idx:
                a = np.empty(n, dtype=object)
                a[:] = [v[i] for v in values]
                arrs.append(a)
            return row_keys(arrs, n=n)
        start = self._seq + 1
        self._seq += n
        return sequential_keys(start, n)

    def _key_of(self, values: tuple) -> int:
        return int(self._keys_for([values])[0])

    def _push(self, values: tuple, diff: int) -> None:
        assert self._node is not None, "subject not attached to a running graph"
        self._node.push(self._key_of(values), values, diff)

    def _remove(self, **kwargs: Any) -> None:
        if not self._pk_cols:
            raise RuntimeError("_remove requires a schema with primary keys")
        values = tuple(kwargs.get(c) for c in self._columns)
        self._node.push(self._key_of(values), values, -1)


class _SubjectDriver:
    """Runs the subject's ``run()`` in a thread (reference: connector thread per
    input, ``src/connectors/mod.rs:91``). A subject exception is captured and
    surfaced by the runtime's main loop (the reference's ErrorReporter channel,
    SURVEY §5.3) instead of dying silently with the thread."""

    virtual = False

    def __init__(self, subject: ConnectorSubject):
        self.subject = subject
        self.thread: threading.Thread | None = None
        self.error: BaseException | None = None
        self._error_pre_stop = False
        self._stopped = False

    def start(self) -> None:
        def target() -> None:
            try:
                self.subject.run()
            except BaseException as e:  # noqa: BLE001 — transported to the run loop
                self._error_pre_stop = not self._stopped
                self.error = e
            finally:
                self.subject.close()

        self.thread = threading.Thread(target=target, daemon=True)
        self.thread.start()

    def failure(self) -> BaseException | None:
        # errors raised after a requested stop (e.g. a socket torn down
        # mid-read) are shutdown noise, not pipeline failures; errors raised
        # before the stop stay visible even once stop() runs in the finally
        # block, so the run loop's post-loop check can still surface them
        return self.error if self._error_pre_stop else None

    def is_finished(self) -> bool:
        node = self.subject._node
        return (
            self.subject._closed
            and (self.thread is None or not self.thread.is_alive())
            and (node is None or not node._pending)
        )

    def stop(self) -> None:
        self._stopped = True
        self.subject.on_stop()


class _StaticStreamSubject(ConnectorSubject):
    """Deterministic timed fixture: events = [(time, key, values, diff)].

    Plays the role of the reference's ``pw.debug.StreamGenerator``
    (``debug/__init__.py:508``): exact logical times, no real threads.
    """

    def __init__(self, events: list[tuple[int, int, tuple, int]], columns: list[str]):
        super().__init__()
        self.events = events
        self._columns = columns

    def run(self) -> None:
        pass


class _TimedInputNode(ops.StreamInputNode):
    """Input node emitting pre-timed events when the tick reaches their time.

    Fast path (r5, incremental-engine throughput): the whole fixture
    columnarizes ONCE (numpy key/diff/time arrays + typed value columns) and
    every tick emits an array slice — no per-event Python in the run loop.
    When persistence hooks the node's push functions (input logging), the
    per-event push path is kept so the log sees every event.

    Not flow-gated: the fixture replays a deterministic pre-timed event list
    (no live producer to backpressure), and gating it would perturb the exact
    logical times the tests pin."""

    flow_gated = False

    def __init__(self, events, columns, np_dtypes, upsert=False, arrays=None):
        super().__init__(columns, np_dtypes, upsert=upsert)
        self.events = events  # sorted by time
        self.idx = 0
        self._times: np.ndarray | None = None
        if arrays is not None:  # pre-columnarized at fixture construction
            self._times, self._keys_arr, self._diffs_arr, self._data_arrs = arrays

    @staticmethod
    def columnarize(events, columns, np_dtypes) -> tuple:
        """(times, keys, diffs, data) arrays for a sorted event list — done
        once at fixture construction so no per-event Python (or fromiter
        pass) runs inside the measured engine loop."""
        from pathway_tpu.engine.blocks import make_column

        n = len(events)
        times = np.fromiter((e[0] for e in events), np.int64, count=n)
        keys = np.fromiter((e[1] for e in events), np.uint64, count=n)
        diffs = np.fromiter((e[3] for e in events), np.int64, count=n)
        rows = [e[2] for e in events]
        data = {
            c: make_column([r[j] for r in rows], np_dtypes.get(c, np.dtype(object)))
            for j, c in enumerate(columns)
        }
        return times, keys, diffs, data

    def _materialize(self) -> None:
        self._times, self._keys_arr, self._diffs_arr, self._data_arrs = (
            self.columnarize(self.events, self.columns, self.np_dtypes)
        )

    def _hooked(self) -> bool:
        # persistence replaces push/push_many with logging wrappers as
        # INSTANCE attributes; their presence forces the per-event path
        return "push" in self.__dict__ or "push_many" in self.__dict__

    def poll(self, time: int):
        from pathway_tpu.engine.blocks import DeltaBatch, net_input_batch
        from pathway_tpu.engine.graph import END_OF_STREAM

        if self.upsert or self._hooked():
            emit_until = self.idx
            while emit_until < len(self.events) and (
                self.events[emit_until][0] <= time or time == END_OF_STREAM
            ):
                emit_until += 1
            if emit_until == self.idx:
                return super().poll(time)
            # one lock + extend for the whole tick's slice, not a lock per event
            self.push_many(
                (key, values, diff)
                for (_t, key, values, diff) in self.events[self.idx : emit_until]
            )
            self.idx = emit_until
            return super().poll(time)

        if time == END_OF_STREAM:
            # parity with StreamInputNode: the close tick emits nothing
            # (drivers hold the run open until every event was emitted)
            return super().poll(time)
        if self._times is None:
            self._materialize()
        emit_until = int(np.searchsorted(self._times, time, side="right"))
        if emit_until <= self.idx:
            return super().poll(time)  # drains stray pushes (none normally)
        sl = slice(self.idx, emit_until)
        # copies, not views: the columnarized fixture arrays are shared across
        # every worker's build and across successive pw.run calls on the same
        # fixture — a downstream in-place mutation of a view would corrupt the
        # fixture for other workers/runs (ADVICE r5)
        # watermark probes (the per-row push path stamps these in push();
        # this columnarized fast lane must stamp them itself)
        import time as _t

        now_ns = _t.time_ns()
        self.wm_rows += emit_until - sl.start
        self.wm_ingest_ns = now_ns
        if self.event_time_index is not None:
            col = self._data_arrs[self.columns[self.event_time_index]][sl]
            try:
                et = float(max(col))
                if self.wm_event_time is None or et > self.wm_event_time:
                    self.wm_event_time = et
            except (TypeError, ValueError):
                pass
        from pathway_tpu.observability.metrics import run_metrics

        run_metrics().note_tick_ingest(time, now_ns)
        batch = DeltaBatch(
            self._keys_arr[sl].copy(),
            self._diffs_arr[sl].copy(),
            {c: a[sl].copy() for c, a in self._data_arrs.items()},
            time,
        )
        self.idx = emit_until
        self.polled_total += emit_until - sl.start
        return [net_input_batch(batch)]

    @property
    def max_time(self) -> int:
        return self.events[-1][0] if self.events else 0


class _TimedDriver:
    virtual = True

    def __init__(self, node_holder: dict):
        self.holder = node_holder

    def start(self) -> None:
        pass

    def is_finished(self) -> bool:
        node = self.holder.get("node")
        return node is not None and node.idx >= len(node.events) and not node._pending

    def stop(self) -> None:
        pass


def read(
    subject: ConnectorSubject,
    *,
    schema: schema_mod.SchemaMetaclass,
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    event_time_column: str | None = None,
    service_class: str = "interactive",
    **kwargs: Any,
) -> Table:
    from pathway_tpu.flow import validate_service_class

    # flow plane (PATHWAY_FLOW=on): ``interactive`` streams always drain at
    # tick start; ``bulk`` (backfill) streams are budget-throttled under
    # pressure so query traffic overtakes them at tick granularity
    service_class = validate_service_class(service_class)
    columns = schema.column_names()
    np_dtypes = schema.np_dtypes()
    subject._columns = columns
    subject._pk_cols = schema.primary_key_columns()
    # observability: the named column drives this input's EVENT-TIME watermark
    # (``/metrics`` pathway_input_watermark; default is processing time)
    event_time_index = (
        columns.index(event_time_column) if event_time_column is not None else None
    )

    if isinstance(subject, _StaticStreamSubject):
        holder: dict[str, Any] = {}
        events = subject.events
        # columnarize once at fixture construction — runs re-using the fixture
        # (each _capture / pw.run builds fresh nodes) share the arrays
        arrays = _TimedInputNode.columnarize(events, columns, np_dtypes)

        def factory() -> Node:
            node = _TimedInputNode(events, columns, np_dtypes, arrays=arrays)
            node.event_time_index = event_time_index
            node.input_name = name or "stream_fixture"
            node.service_class = service_class
            holder["node"] = node
            return node

        def hook(node: Node, runtime: Any) -> None:
            if runtime is not None:
                runtime.register_connector(_TimedDriver(holder))

        lnode = LogicalNode(factory, [], name=name or "stream_fixture", runtime_hook=hook)
        return Table(lnode, schema, Universe())

    def factory() -> Node:
        node = ops.StreamInputNode(
            columns, np_dtypes, upsert=subject._session_type == "upsert"
        )
        node.event_time_index = event_time_index
        node.input_name = name or getattr(subject, "datasource_name", None) or "python"
        node.service_class = service_class
        subject._node = node
        return node

    def hook(node: Node, runtime: Any) -> None:
        if runtime is not None:
            runtime.register_connector(_SubjectDriver(subject))

    lnode = LogicalNode(factory, [], name=name or "python_connector", runtime_hook=hook)
    return Table(lnode, schema, Universe())


read_subject = read


def read_partitioned(
    make_subject,
    *,
    schema: schema_mod.SchemaMetaclass,
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    service_class: str = "interactive",
) -> Table:
    """Partition-per-worker ingest (reference: Kafka read partition-per-worker,
    ``worker-architecture.md:36-47``; r5 kills the worker-0 SOLO pin).

    ``make_subject(worker_index, n_workers) -> ConnectorSubject`` builds one
    subject per worker, each owning a disjoint slice of the source (e.g. Kafka
    partitions ``p % n_workers == worker_index``). Every worker's node polls
    locally (``local_source``); downstream co-location happens through the
    normal key exchange. Under a single-worker runtime this degenerates to
    ``read(make_subject(0, 1), ...)``.
    """
    from pathway_tpu.flow import validate_service_class
    from pathway_tpu.internals.logical import current_build

    service_class = validate_service_class(service_class)
    columns = schema.column_names()
    np_dtypes = schema.np_dtypes()

    def factory() -> Node:
        ctx = current_build()
        w = ctx.worker_index if ctx is not None else 0
        n = ctx.n_workers if ctx is not None else 1
        subject = make_subject(w, n)
        subject._columns = columns
        subject._pk_cols = schema.primary_key_columns()
        node = ops.StreamInputNode(
            columns, np_dtypes, upsert=subject._session_type == "upsert"
        )
        node.local_source = True  # poll on the owning worker, not worker 0
        node.source_worker = w
        node.service_class = service_class
        subject._node = node
        if ctx is not None and ctx.register is not None:
            ctx.register(_SubjectDriver(subject))
        return node

    lnode = LogicalNode(factory, [], name=name or "python_connector_partitioned")
    return Table(lnode, schema, Universe())
