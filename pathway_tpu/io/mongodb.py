"""MongoDB writer (reference: ``MongoWriter`` ``src/connectors/data_storage.rs:1757``).
Positive diffs insert documents (with time/diff fields); retractions delete the
matching document. Requires ``pymongo`` (not in this image; import-gated)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import _plain


def write(table: Table, connection_string: str, database: str, collection: str, **kwargs: Any) -> None:
    try:
        from pymongo import MongoClient
    except ImportError:
        raise NotImplementedError(
            "pw.io.mongodb requires pymongo, which is not available in this environment"
        ) from None

    coll = MongoClient(connection_string)[database][collection]
    cols = table.column_names()

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            doc = {c: _plain(v) for c, v in zip(columns, row)}
            if diff > 0:
                doc["_pw_key"] = str(int(key))
                doc["time"] = batch.time
                coll.insert_one(doc)
            else:
                coll.delete_one({"_pw_key": str(int(key))})

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"mongodb:{database}.{collection}",
    )._register_as_output()
