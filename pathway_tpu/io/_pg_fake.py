"""In-process file-backed Postgres stand-in (DBAPI shape) for the driverless
test image.

``psycopg2`` is not available here, but the exactly-once delivery tests need a
sink with REAL transaction semantics that survives SIGKILL: committed state
must be durable across processes, uncommitted state must vanish with the dying
connection. :class:`FakePostgres` provides exactly that — a pickle file updated
tmp+rename+fsync per ``commit()`` (the same atomic-replace discipline as the
persistence backends), with a regex interpreter for the narrow SQL dialect the
``io.postgres`` writers emit:

- ``CREATE TABLE [IF NOT EXISTS] t (col TYPE ..., PRIMARY KEY (a, b))``
- ``INSERT INTO t (cols) VALUES (%s, ...)`` with optional
  ``ON CONFLICT (pk) DO UPDATE SET c = EXCLUDED.c, ...``
- ``DELETE FROM t WHERE a = %s [AND b = %s ...]``
- ``SELECT <1 | * | cols> FROM t [WHERE a = %s ...] [ORDER BY cols]``

Inject it through the settings dict:
``{"connection_factory": FakePostgres(path).connect}``.
"""

from __future__ import annotations

import os
import pickle
import re
import threading


class FakePostgresError(Exception):
    pass


# -- durable state -------------------------------------------------------------
def _load(path: str) -> dict:
    try:
        with open(path, "rb") as fh:
            return pickle.load(fh)
    except (FileNotFoundError, EOFError, pickle.UnpicklingError):
        return {}


def _store(path: str, state: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "wb") as fh:
        pickle.dump(state, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


# -- SQL dialect ---------------------------------------------------------------
_CREATE = re.compile(
    r"^\s*CREATE\s+TABLE\s+(?:IF\s+NOT\s+EXISTS\s+)?(\w+)\s*\((.*)\)\s*$",
    re.I | re.S,
)
_INSERT = re.compile(
    r"^\s*INSERT\s+INTO\s+(\w+)\s*\(([^)]*)\)\s*VALUES\s*\(([^)]*)\)"
    r"(?:\s+ON\s+CONFLICT\s*\(([^)]*)\)\s*DO\s+UPDATE\s+SET\s+(.*))?\s*$",
    re.I | re.S,
)
_DELETE = re.compile(r"^\s*DELETE\s+FROM\s+(\w+)\s+WHERE\s+(.*)$", re.I | re.S)
_SELECT = re.compile(
    r"^\s*SELECT\s+(.*?)\s+FROM\s+(\w+)"
    r"(?:\s+WHERE\s+(.+?))?(?:\s+ORDER\s+BY\s+(.+?))?\s*$",
    re.I | re.S,
)
_WHERE_EQ = re.compile(r"(\w+)\s*=\s*%s", re.I)


def _split_top(text: str) -> list[str]:
    """Split on commas outside parentheses (column defs vs composite PK)."""
    out: list[str] = []
    depth = 0
    cur: list[str] = []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "," and depth == 0:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return out


def _parse_create(name: str, body: str) -> tuple:
    cols: list[str] = []
    pk: list[str] = []
    for item in _split_top(body):
        up = item.upper()
        if up.startswith("PRIMARY KEY"):
            inner = item[item.index("(") + 1 : item.rindex(")")]
            pk = [c.strip() for c in inner.split(",") if c.strip()]
            continue
        col = item.split()[0]
        cols.append(col)
        if "PRIMARY KEY" in up:
            pk.append(col)
    return ("create", name, cols, pk)


def _apply(state: dict, op: tuple) -> None:
    kind = op[0]
    if kind == "create":
        _, name, cols, pk = op
        state.setdefault(name, {"cols": cols, "pk": pk, "rows": []})
        return
    name = op[1]
    t = state.get(name)
    if t is None:
        raise FakePostgresError(f'relation "{name}" does not exist')
    if kind == "insert":
        _, _name, cols, values, conflict_pk = op
        row = dict(zip(cols, values))
        if conflict_pk:
            for existing in t["rows"]:
                if all(existing.get(c) == row[c] for c in conflict_pk):
                    existing.update(row)
                    return
            t["rows"].append(row)
            return
        pk = t.get("pk") or []
        if pk and all(c in row for c in pk):
            for existing in t["rows"]:
                if all(existing.get(c) == row.get(c) for c in pk):
                    raise FakePostgresError(
                        f"duplicate key value violates unique constraint "
                        f'on "{name}" ({", ".join(pk)})'
                    )
        t["rows"].append(row)
    elif kind == "delete":
        _, _name, where = op
        t["rows"] = [
            r
            for r in t["rows"]
            if not all(r.get(c) == v for c, v in where)
        ]


# -- DBAPI surface -------------------------------------------------------------
class FakeCursor:
    def __init__(self, con: "FakePostgresConnection"):
        self._con = con
        self._result: list[tuple] = []

    def __enter__(self) -> "FakeCursor":
        return self

    def __exit__(self, *a) -> bool:
        return False

    def execute(self, sql: str, params=()) -> None:
        self._result = self._con._execute(sql, tuple(params or ()))

    def fetchone(self):
        return self._result[0] if self._result else None

    def fetchall(self) -> list[tuple]:
        return list(self._result)

    def close(self) -> None:
        pass


class FakePostgresConnection:
    """One transaction at a time: executes buffer ops, ``commit()`` re-reads
    the base file, applies them, and atomically replaces it — a SIGKILL before
    commit leaves the file untouched (the crash-window contract the delivery
    transport's per-epoch transactions rely on)."""

    def __init__(self, path: str):
        self.path = path
        self._pending: list[tuple] = []
        self._lock = threading.Lock()

    def cursor(self) -> FakeCursor:
        return FakeCursor(self)

    def commit(self) -> None:
        with self._lock:
            if not self._pending:
                return
            state = _load(self.path)
            for op in self._pending:
                _apply(state, op)
            _store(self.path, state)
            self._pending = []

    def rollback(self) -> None:
        with self._lock:
            self._pending = []

    def close(self) -> None:
        self._pending = []

    # -- statement interpreter -------------------------------------------------
    def _execute(self, sql: str, params: tuple) -> list[tuple]:
        m = _CREATE.match(sql)
        if m:
            self._pending.append(_parse_create(m.group(1), m.group(2)))
            return []
        m = _INSERT.match(sql)
        if m:
            name, cols_s, values_s, conflict_s, _set_s = m.groups()
            cols = [c.strip() for c in cols_s.split(",") if c.strip()]
            n_ph = values_s.count("%s")
            if n_ph != len(params) or n_ph != len(cols):
                raise FakePostgresError(
                    f"INSERT INTO {name}: {len(cols)} columns, {n_ph} "
                    f"placeholders, {len(params)} parameters"
                )
            conflict_pk = (
                [c.strip() for c in conflict_s.split(",") if c.strip()]
                if conflict_s
                else None
            )
            self._pending.append(
                ("insert", name, cols, list(params), conflict_pk)
            )
            return []
        m = _DELETE.match(sql)
        if m:
            name, where_s = m.groups()
            where = list(zip(_WHERE_EQ.findall(where_s), params))
            self._pending.append(("delete", name, where))
            return []
        m = _SELECT.match(sql)
        if m:
            return self._select(*m.groups(), params)
        raise FakePostgresError(f"unsupported SQL: {sql!r}")

    def _view(self) -> dict:
        state = _load(self.path)
        for op in self._pending:
            _apply(state, op)
        return state

    def _select(self, cols_expr, name, where_s, order_s, params) -> list[tuple]:
        t = self._view().get(name)
        if t is None:
            raise FakePostgresError(f'relation "{name}" does not exist')
        rows = t["rows"]
        if where_s:
            pairs = list(zip(_WHERE_EQ.findall(where_s), params))
            rows = [r for r in rows if all(r.get(c) == v for c, v in pairs)]
        if order_s:
            keys = [c.strip() for c in order_s.split(",") if c.strip()]
            rows = sorted(rows, key=lambda r: tuple(r.get(c) for c in keys))
        cols_expr = cols_expr.strip()
        if cols_expr == "1":
            return [(1,) for _ in rows]
        if cols_expr == "*":
            cols = t["cols"]
        else:
            cols = [c.strip() for c in cols_expr.split(",") if c.strip()]
        return [tuple(r.get(c) for c in cols) for r in rows]


class FakePostgres:
    """Handle on one database file; every :meth:`connect` call opens an
    independent transaction scope over the same durable state."""

    def __init__(self, path: str):
        self.path = path

    def connect(self) -> FakePostgresConnection:
        return FakePostgresConnection(self.path)

    def dump(self, table: str, order_by: list[str] | None = None) -> list[tuple]:
        """Committed rows of ``table`` as column-ordered tuples (test oracle)."""
        t = _load(self.path).get(table)
        if t is None:
            return []
        rows = t["rows"]
        if order_by:
            rows = sorted(rows, key=lambda r: tuple(r.get(c) for c in order_by))
        return [tuple(r.get(c) for c in t["cols"]) for r in rows]
