"""Kafka connector (reference: ``python/pathway/io/kafka`` over Rust
``KafkaReader``/``KafkaWriter``, ``src/connectors/data_storage.rs:712,1406``).

Two transports behind one API:

- ``MockKafkaBroker`` — an in-process/file-backed partitioned log. With a ``path``
  it is durable across processes (each partition is an append-only jsonl file), so
  kill/restart recovery and multi-process tests run without a broker daemon —
  the role of the reference's dockerized Kafka fixtures
  (``integration_tests/kafka/``).
- real Kafka via ``rdkafka_settings`` — requires a client library that is not in
  this image; gated with a clear error (dependency gate, not a stub).

Reads are partition-aware: a reader owns an explicit partition set, so a
multi-worker runtime can assign disjoint partitions per worker (the reference
reads Kafka partition-per-worker, ``worker-architecture.md:36-47``).
"""

from __future__ import annotations

import json as _json
import os
import threading
import time as _time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import (
    Formatter,
    Parser,
    RawMessage,
    formatter_for,
    parser_for,
)


class MockKafkaBroker:
    """Partitioned append-only message log.

    In-memory by default; give ``path`` for a durable on-disk log shared across
    processes. Messages are (key, value) string/bytes pairs; per-partition order
    is total, cross-partition order is not — exactly Kafka's contract.
    """

    def __init__(self, path: str | None = None):
        self.path = path
        self._mem: dict[str, list[list[tuple[Any, Any]]]] = {}
        self._lock = threading.Lock()
        if path:
            os.makedirs(path, exist_ok=True)

    # ---- admin ----
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            self._ensure_topic_locked(topic, partitions)

    def _ensure_topic_locked(self, topic: str, partitions: int) -> None:
        if self.path:
            tdir = os.path.join(self.path, topic)
            os.makedirs(tdir, exist_ok=True)
            for p in range(partitions):
                fp = self._file(topic, p)
                if not os.path.exists(fp):
                    open(fp, "a").close()
        else:
            plist = self._mem.setdefault(topic, [])
            while len(plist) < partitions:
                plist.append([])

    def partitions(self, topic: str) -> int:
        if self.path:
            tdir = os.path.join(self.path, topic)
            if not os.path.isdir(tdir):
                return 0
            return len([f for f in os.listdir(tdir) if f.startswith("partition_")])
        return len(self._mem.get(topic, []))

    def _file(self, topic: str, partition: int) -> str:
        return os.path.join(self.path, topic, f"partition_{partition:04d}.jsonl")

    # ---- produce ----
    def produce(
        self,
        topic: str,
        value: bytes | str,
        key: bytes | str | None = None,
        partition: int | None = None,
        headers: dict | None = None,
    ) -> None:
        n = self.partitions(topic)
        if n == 0:
            self.create_topic(topic, 1)
            n = 1
        if partition is None:
            partition = (hash(key) % n) if key is not None else 0
        with self._lock:
            self._append_locked(topic, partition, key, value, headers)

    def produce_batch(self, msgs: list[dict], marker: dict | None = None) -> None:
        """Atomically append a batch (each msg ``{"topic", "partition", "key",
        "value", "headers"}``) plus an optional trailing commit marker — the
        delivery plane's single-locked-append publish path: a concurrent
        ``fetch`` never observes a marker without its batch."""
        all_msgs = list(msgs)
        if marker is not None:
            all_msgs.append(dict(marker))
        with self._lock:
            for m in all_msgs:
                p = m.get("partition")
                self._append_locked(
                    m["topic"],
                    0 if p is None else int(p),
                    m.get("key"),
                    m["value"],
                    m.get("headers"),
                )

    def _append_locked(self, topic, partition, key, value, headers=None) -> None:
        self._ensure_topic_locked(topic, partition + 1)
        if isinstance(value, bytes):
            value = value.decode(errors="replace")
        if isinstance(key, bytes):
            key = key.decode(errors="replace")
        rec: dict = {"k": key, "v": value}
        if headers:
            # optional jsonl key: logs written before headers existed (or by
            # plain producers) read back with h absent
            rec["h"] = dict(headers)
        if self.path:
            with open(self._file(topic, partition), "a") as fh:
                fh.write(_json.dumps(rec) + "\n")
                fh.flush()
        else:
            self._mem[topic][partition].append(rec)

    # ---- consume ----
    def fetch(self, topic: str, partition: int, offset: int) -> list[tuple[Any, Any]]:
        """All messages in ``partition`` from ``offset`` (message index) on."""
        return [
            (r["k"], r["v"]) for r in self.fetch_records(topic, partition, offset)
        ]

    def fetch_records(self, topic: str, partition: int, offset: int) -> list[dict]:
        """Like :meth:`fetch` but returns full records (``{"k", "v"}`` plus
        ``"h"`` headers when present) — ``delivery.read_committed`` needs the
        idempotence headers to dedupe crash-window re-publishes."""
        if self.path:
            fp = self._file(topic, partition)
            if not os.path.exists(fp):
                return []
            out = []
            with open(fp) as fh:
                for i, line in enumerate(fh):
                    if i < offset or not line.strip():
                        continue
                    out.append(_json.loads(line))
            return out
        with self._lock:
            plist = self._mem.get(topic)
            if plist is None or partition >= len(plist):
                return []
            return [dict(r) for r in plist[partition][offset:]]


def _client_module(settings: dict):
    """Resolve a confluent-kafka-shaped client module for ``rdkafka_settings``
    dicts: the ``client_factory`` key injects any object exposing
    ``Consumer``/``Producer``/``TopicPartition`` (how CI exercises the real
    wire path on this clientless image — ``tests/test_gated_connectors.py``);
    otherwise ``confluent_kafka`` is imported."""
    factory = settings.get("client_factory")
    if factory is not None:
        return factory
    try:
        import confluent_kafka

        return confluent_kafka
    except ImportError:
        raise NotImplementedError(
            "real Kafka requires the confluent-kafka client (or a "
            "client_factory= entry in the settings dict); pass a "
            "MockKafkaBroker (optionally file-backed) instead"
        ) from None


def _conf_of(settings: dict) -> dict:
    conf = {k: v for k, v in settings.items() if k != "client_factory"}
    conf.setdefault("group.id", "pathway")
    return conf


def _kafka_event_key(subject, topic: str, partition: int, offset: int, event_idx: int, values: tuple) -> int:
    """Row key for one parsed event. pk schemas key by content (updates net in
    place); otherwise the key derives from (topic, partition, offset,
    event-within-message) — deterministic across worker counts and arrival
    interleavings, unlike a per-subject sequential counter (which would
    collide between workers' subjects), and unique per event even when one
    message parses into several rows."""
    if subject._pk_cols:
        return subject._key_of(values)
    from pathway_tpu.internals.keys import stable_hash_obj

    return int(stable_hash_obj(("kafka", topic, partition, offset, event_idx)))


def _read_real(
    settings: dict,
    topic: str,
    schema,
    the_parser: Parser,
    mode: str,
    partitions: list[int] | None,
    poll_interval: float,
    name: str | None,
    service_class: str = "interactive",
):
    """Consumer-driven read over the wire protocol client (reference
    ``KafkaReader``, ``src/connectors/data_storage.rs:712``): assigned
    partitions, per-partition offsets for the persistence seek contract,
    static mode bounded by the watermark offsets captured at start.
    Partition-per-worker: each worker's subject assigns only partitions
    ``p % n_workers == worker`` (``worker-architecture.md:36-47``)."""
    from pathway_tpu.io.python import (
        ConnectorSubject,
        read_partitioned as py_read_partitioned,
    )

    ck = _client_module(settings)

    class _RealKafkaSubject(ConnectorSubject):
        def __init__(self, worker: int = 0, n_workers: int = 1) -> None:
            super().__init__()
            self.worker = worker
            self.n_workers = n_workers
            self._stop = False
            self._offsets: dict[int, int] = {}
            self.sync_lock = threading.Lock()

        def run(self) -> None:
            consumer = ck.Consumer(_conf_of(settings))
            try:
                if partitions is not None:
                    # explicit assignment: tolerate transient metadata (topic
                    # mid-creation); consumption starts once leaders resolve
                    parts = partitions
                else:
                    md = consumer.list_topics(topic)
                    tmeta = (
                        md.topics.get(topic) if hasattr(md.topics, "get") else None
                    )
                    terr = getattr(tmeta, "error", None)
                    if tmeta is None or terr or not getattr(tmeta, "partitions", None):
                        raise RuntimeError(
                            f"kafka topic {topic!r} unavailable: "
                            f"{terr or 'unknown topic / no partitions'}"
                        )
                    parts = sorted(tmeta.partitions.keys())
                parts = [p for p in parts if p % self.n_workers == self.worker]
                if not parts:
                    return  # more workers than partitions: this slice is empty
                # fresh partitions start at OFFSET_BEGINNING (an absolute 0
                # can be out of retention range and silently jump to the log
                # end via auto.offset.reset)
                begin = getattr(ck, "OFFSET_BEGINNING", 0)
                consumer.assign(
                    [
                        ck.TopicPartition(topic, p, self._offsets.get(p, begin))
                        for p in parts
                    ]
                )
                ends: dict[int, int] | None = None
                if mode == "static":
                    ends = {}
                    for p in parts:
                        _lo, hi = consumer.get_watermark_offsets(
                            ck.TopicPartition(topic, p)
                        )
                        ends[p] = hi
                while not self._stop:
                    msg = consumer.poll(poll_interval)
                    if msg is None:
                        if ends is not None and all(
                            self._offsets.get(p, 0) >= ends[p] for p in parts
                        ):
                            return
                        continue
                    err = msg.error()
                    if err is not None:
                        # partition-EOF events are benign position markers;
                        # anything else (auth, unknown topic, broker down) must
                        # surface through the connector error channel, not spin
                        eof = getattr(
                            getattr(ck, "KafkaError", None), "_PARTITION_EOF", None
                        )
                        if eof is not None and getattr(err, "code", lambda: None)() == eof:
                            continue
                        raise RuntimeError(f"kafka consumer error: {err}")
                    with self.sync_lock:
                        assert self._node is not None
                        self._node.push_many(
                            (
                                _kafka_event_key(
                                    self, topic, msg.partition(), msg.offset(), j, ev.values
                                ),
                                # Debezium tombstones: pk-keyed valueless event
                                # — upsert sessions delete, native sessions
                                # drop it (the op:"d" envelope already
                                # retracted the row)
                                None if ev.tombstone else ev.values,
                                ev.diff,
                            )
                            for j, ev in enumerate(
                                the_parser.parse(
                                    RawMessage(
                                        value=msg.value(),
                                        key=msg.key(),
                                        metadata={"partition": msg.partition()},
                                    )
                                )
                            )
                        )
                        self._offsets[msg.partition()] = msg.offset() + 1
                    if ends is not None and all(
                        self._offsets.get(p, 0) >= ends[p] for p in parts
                    ):
                        return
            finally:
                consumer.close()

        # persistence contract (OffsetAntichain analogue + Reader::seek).
        # The sequential row-key counter travels with the offsets: a restart
        # that replays N logged events but reset _seq would hand the next
        # live messages the SAME row keys the replayed events already own.
        def offset_state(self) -> dict:
            return {"partitions": dict(self._offsets), "seq": self._seq}

        def seek(self, state: dict) -> None:
            if "partitions" in state:
                self._offsets = {
                    int(k): int(v) for k, v in state["partitions"].items()
                }
                self._seq = int(state.get("seq", 0))
            else:  # legacy bare partition map
                self._offsets = {int(k): int(v) for k, v in state.items()}

        def on_stop(self) -> None:
            self._stop = True

    return py_read_partitioned(
        lambda w, n: _RealKafkaSubject(w, n),
        schema=schema,
        name=name or f"kafka:{topic}",
        service_class=service_class,
    )


def read(
    broker: MockKafkaBroker | dict,
    topic: str,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "json",  # noqa: A002
    mode: str = "streaming",
    parser: Parser | None = None,
    partitions: list[int] | None = None,
    poll_interval: float = 0.05,
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    service_class: str = "interactive",
    **kwargs: Any,
) -> Table:
    """Consume ``topic`` into a table. ``mode="static"`` drains the current log
    then finishes; ``"streaming"`` keeps tailing until the run is stopped."""
    if schema is None:
        if format in ("plaintext", "raw"):
            schema = schema_mod.schema_from_types(data=str)
        elif format == "binary":
            schema = schema_mod.schema_from_types(data=bytes)
        else:
            raise ValueError("schema required for json/csv kafka formats")
    the_parser = parser or parser_for(format, schema)
    if isinstance(broker, dict):
        return _read_real(
            broker, topic, schema, the_parser, mode, partitions, poll_interval, name,
            service_class=service_class,
        )

    from pathway_tpu.io.python import (
        ConnectorSubject,
        read_partitioned as py_read_partitioned,
    )

    class _KafkaSubject(ConnectorSubject):
        """One worker's slice of the topic. ``worker``/``n_workers`` pick the
        partition subset (``p % n_workers == worker``); under a single-worker
        runtime the subject owns every partition — the pre-r5 behavior."""

        def __init__(self, worker: int = 0, n_workers: int = 1) -> None:
            super().__init__()
            self.worker = worker
            self.n_workers = n_workers
            self._stop = False
            self._offsets: dict[int, int] = {}
            # push-batch + offset-advance are atomic under this lock so a
            # persistence flush always sees offsets matching the pushed events
            self.sync_lock = threading.Lock()

        def _my_parts(self) -> list[int]:
            parts = partitions
            if parts is None:
                parts = list(range(max(1, broker.partitions(topic))))
            return [p for p in parts if p % self.n_workers == self.worker]

        def run(self) -> None:
            while not self._stop:
                progressed = False
                for p in self._my_parts():
                    off = self._offsets.get(p, 0)
                    msgs = broker.fetch(topic, p, off)
                    if not msgs:
                        continue
                    progressed = True
                    with self.sync_lock:
                        events = []
                        for i, (key, value) in enumerate(msgs):
                            for j, ev in enumerate(
                                the_parser.parse(
                                    RawMessage(value=value, key=key, metadata={"partition": p})
                                )
                            ):
                                events.append(
                                    (
                                        _kafka_event_key(
                                            self, topic, p, off + i, j, ev.values
                                        ),
                                        # tombstone → valueless keyed event
                                        # (upsert deletes, native drops)
                                        None if ev.tombstone else ev.values,
                                        ev.diff,
                                    )
                                )
                        assert self._node is not None
                        self._node.push_many(events)
                        self._offsets[p] = off + len(msgs)
                if not progressed:
                    if mode == "static":
                        return
                    _time.sleep(poll_interval)

        # ---- persistence contract (the per-source OffsetAntichain analogue,
        # src/persistence/frontier.rs:12 + Reader::seek). _seq travels with
        # the offsets so restarted live messages never reuse replayed keys ----
        def offset_state(self) -> dict:
            return {"partitions": dict(self._offsets), "seq": self._seq}

        def seek(self, state: dict) -> None:
            if "partitions" in state:
                self._offsets = {
                    int(k): int(v) for k, v in state["partitions"].items()
                }
                self._seq = int(state.get("seq", 0))
            else:  # legacy bare partition map
                self._offsets = {int(k): int(v) for k, v in state.items()}

        def on_stop(self) -> None:
            self._stop = True

    return py_read_partitioned(
        lambda w, n: _KafkaSubject(w, n),
        schema=schema,
        name=name or f"kafka:{topic}",
        service_class=service_class,
    )


def write(
    table: Table,
    broker: MockKafkaBroker | dict,
    topic: str,
    *,
    format: str = "json",  # noqa: A002
    formatter: Formatter | None = None,
    key_column: str | None = None,
    delivery: str | None = None,
    partitions: int = 1,
    **kwargs: Any,
) -> None:
    """Produce every output diff of ``table`` to ``topic``.

    ``delivery="exactly_once"`` (or ``PATHWAY_DELIVERY=exactly_once``) routes
    rows through the durable delivery ledger: staged per epoch, frozen at
    operator-snapshot recovery points, and published transactionally (real
    clients with ``transactional.id``) or with ``(sink, epoch, partition,
    seq)`` dedupe headers that ``delivery.read_committed`` consumers drop —
    byte-identical downstream state across SIGKILL/restart/rescale.
    ``partitions`` spreads delivery output across mock-broker partitions by a
    stable hash of the message key."""
    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode

    cols = table.column_names()
    fmt = formatter or formatter_for(format, cols, **kwargs)
    key_idx = cols.index(key_column) if key_column else None

    from pathway_tpu import delivery as _delivery

    if _delivery.resolve_mode(delivery) == "exactly_once":
        n_parts = max(1, partitions)
        if not isinstance(broker, dict):
            broker.create_topic(topic, n_parts)
        transport = _delivery.KafkaDeliveryTransport(broker, topic)
        writer = _delivery.LedgerWriter(f"kafka.{topic}", transport)

        def on_batch_ledger(batch, columns) -> None:
            for key, diff, row in batch.rows():
                payload = fmt.format(int(key), row, batch.time, diff)
                if isinstance(payload, bytes):
                    payload = payload.decode(errors="replace")
                mkey = str(row[key_idx]) if key_idx is not None else None
                writer.append(
                    _delivery.stable_partition(mkey, n_parts), (mkey, payload)
                )

        def _ledger_node():
            node = ops.CallbackOutputNode(
                cols,
                on_batch_ledger,
                sink_state=writer.sink_state,
                restore_sink=writer.restore_sink,
            )
            # the persistence plane scans for this attribute to bind the
            # writer's ledger at graph build (snapshots._bind_delivery)
            node.delivery_writer = writer
            return node

        LogicalNode(
            _ledger_node, [table._node], name=f"kafka_write:{topic}"
        )._register_as_output()
        return

    if isinstance(broker, dict):
        # wire-protocol producer (reference KafkaWriter, data_storage.rs:1406)
        ck = _client_module(broker)
        producer = ck.Producer(_conf_of(broker))

        def on_batch(batch, columns) -> None:
            for key, diff, row in batch.rows():
                payload = fmt.format(int(key), row, batch.time, diff)
                mkey = str(row[key_idx]) if key_idx is not None else None
                producer.produce(topic, value=payload, key=mkey)
            producer.flush()

    else:
        broker.create_topic(topic, 1)

        def on_batch(batch, columns) -> None:
            for key, diff, row in batch.rows():
                payload = fmt.format(int(key), row, batch.time, diff)
                mkey = str(row[key_idx]) if key_idx is not None else None
                broker.produce(topic, payload, key=mkey)

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"kafka_write:{topic}",
    )._register_as_output()
