"""HTTP connector & REST request/response server.

Reference: ``python/pathway/io/http`` — ``rest_connector`` (``_server.py:624``) is
the request/response bridge that makes streaming RAG servers possible. Implemented
in this package in ``_server.py`` on aiohttp.
"""

from pathway_tpu.fabric.replica import serve_table
from pathway_tpu.io.http._server import (
    EndpointDocumentation,
    PathwayWebserver,
    openapi_spec,
    rest_connector,
    response_writer,
)


def read(
    url: str,
    *,
    schema=None,
    format: str = "json",  # noqa: A002
    mode: str = "streaming",
    poll_interval: float = 1.0,
    request_kwargs: dict | None = None,
    name: str | None = None,
    **kwargs,
):
    """Poll ``url`` and parse each response body through the format's Parser
    (reference: ``python/pathway/io/http`` read side)."""
    import time as _time

    import requests as _requests

    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.io._format import RawMessage, parser_for
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    if schema is None:
        schema = schema_mod.schema_from_types(data=str)
    parser = parser_for(format, schema)

    class _HttpSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._stop = False

        def run(self) -> None:
            while not self._stop:
                resp = _requests.get(url, **(request_kwargs or {}))
                for ev in parser.parse(RawMessage(value=resp.content)):
                    self._push(ev.values, diff=ev.diff)
                if mode == "static":
                    return
                _time.sleep(poll_interval)

        def on_stop(self) -> None:
            self._stop = True

    return py_read(_HttpSubject(), schema=schema, name=name or f"http:{url}")


def write(
    table,
    url: str,
    *,
    method: str = "POST",
    format: str = "json",  # noqa: A002
    request_kwargs: dict | None = None,
    **kwargs,
) -> None:
    """Send every output diff to ``url`` (reference: io/http write side)."""
    import requests as _requests

    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode
    from pathway_tpu.io._format import formatter_for

    cols = table.column_names()
    fmt = formatter_for(format, cols, **kwargs)

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            _requests.request(
                method, url, data=fmt.format(int(key), row, batch.time, diff),
                **(request_kwargs or {}),
            )

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"http_write:{url}",
    )._register_as_output()


__all__ = [
    "EndpointDocumentation",
    "PathwayWebserver",
    "openapi_spec",
    "read",
    "response_writer",
    "rest_connector",
    "serve_table",
    "write",
]
