"""HTTP connector & REST request/response server.

Reference: ``python/pathway/io/http`` — ``rest_connector`` (``_server.py:624``) is
the request/response bridge that makes streaming RAG servers possible. Implemented
in this package in ``_server.py`` on aiohttp.
"""

from pathway_tpu.io.http._server import PathwayWebserver, rest_connector, response_writer

__all__ = ["rest_connector", "response_writer", "PathwayWebserver"]
