"""REST request/response connector.

Mirrors the reference's ``python/pathway/io/http/_server.py`` (``PathwayWebserver``
aiohttp server ``:329``, ``rest_connector`` ``:624``, ``RestServerSubject`` ``:490``):
an HTTP request becomes a row in a streaming queries table (keyed by a request id);
the paired ``response_writer`` subscribes to a result table and resolves the stored
future for that id, completing the HTTP response. Queries are append-only ("as-of-now"
discipline) — results for a request are served once and not retracted.
"""

from __future__ import annotations

import asyncio
import json as _json
import threading
from typing import Any

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import splitmix64
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe


def _jsonable(v: Any) -> Any:
    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


class PathwayWebserver:
    """One aiohttp server shared by many rest_connector routes
    (reference ``_server.py:329``)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        self._routes: list[tuple[str, list[str], Any]] = []
        self._started = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None

    def _add_route(self, route: str, methods: list[str], handler: Any) -> None:
        self._routes.append((route, methods, handler))

    def start(self) -> None:
        if self._thread is not None:
            return

        import aiohttp.web as web

        app = web.Application()
        for route, methods, handler in self._routes:
            for m in methods:
                app.router.add_route(m, route, handler)

        def serve() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, self.host, self.port)
            loop.run_until_complete(site.start())
            self._runner = runner
            self._started.set()
            loop.run_forever()
            loop.run_until_complete(runner.cleanup())

        self._thread = threading.Thread(target=serve, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


class _RestDriver:
    """Connector driver: the server lives for the duration of the run."""

    virtual = False

    def __init__(self, webserver: PathwayWebserver):
        self.webserver = webserver

    def start(self) -> None:
        self.webserver.start()

    def is_finished(self) -> bool:
        return False  # unbounded; stopped via runtime.request_stop()

    def stop(self) -> None:
        self.webserver.stop()


class _RestState:
    def __init__(self) -> None:
        self.node: ops.StreamInputNode | None = None
        self.futures: dict[int, asyncio.Future] = {}
        self.seq = 0
        self.lock = threading.Lock()


def rest_connector(
    host: str = "0.0.0.0",
    port: int = 8080,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: schema_mod.SchemaMetaclass | None = None,
    methods: tuple[str, ...] = ("POST",),
    autocommit_duration_ms: int | None = 20,
    keep_queries: bool = False,
    delete_completed_queries: bool | None = None,
    request_validator: Any = None,
    documentation: Any = None,
) -> tuple[Table, Any]:
    """Returns ``(queries_table, response_writer)``."""
    ws = webserver or PathwayWebserver(host=host, port=port)
    if schema is None:
        schema = schema_mod.schema_from_types(query=str)
    columns = schema.column_names()
    np_dtypes = schema.np_dtypes()
    dtypes = schema.dtypes()
    state = _RestState()

    import aiohttp.web as web

    async def handler(request: "web.Request") -> "web.Response":
        if request.method == "GET":
            payload = dict(request.rel_url.query)
        else:
            try:
                payload = await request.json()
            except Exception:
                payload = {"query": await request.text()}
        if request_validator is not None:
            try:
                request_validator(payload)
            except Exception as e:
                return web.json_response({"error": str(e)}, status=400)
        values = []
        for c in columns:
            v = payload.get(c)
            d = dt.unoptionalize(dtypes[c])
            if d == dt.JSON and v is not None and not isinstance(v, Json):
                v = Json(v)
            values.append(v)
        with state.lock:
            state.seq += 1
            key = int(splitmix64(np.asarray([state.seq], dtype=np.uint64))[0])
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        state.futures[key] = fut
        assert state.node is not None, "rest_connector: engine not running"
        state.node.push(key, tuple(values), 1)
        try:
            result = await asyncio.wait_for(fut, timeout=120)
        except asyncio.TimeoutError:
            state.futures.pop(key, None)
            return web.json_response({"error": "timeout"}, status=504)
        return web.json_response(_jsonable(result))

    ws._add_route(route, list(methods), handler)

    def factory() -> Node:
        node = ops.StreamInputNode(columns, np_dtypes)
        state.node = node
        return node

    def hook(node: Node, runtime: Any) -> None:
        if runtime is not None:
            runtime.register_connector(_RestDriver(ws))

    lnode = LogicalNode(factory, [], name=f"rest:{route}", runtime_hook=hook)
    queries = Table(lnode, schema, Universe())

    def response_writer(result_table: Table) -> None:
        cols = result_table.column_names()

        def on_change(key: int, row: dict, time: int, is_addition: bool) -> None:
            if not is_addition:
                return
            fut = state.futures.pop(int(key), None)
            if fut is None:
                return
            if "result" in row and len(cols) <= 2:
                value = row["result"]
            else:
                value = row
            loop = fut.get_loop()
            loop.call_soon_threadsafe(
                lambda: fut.set_result(value) if not fut.done() else None
            )

        from pathway_tpu.io._subscribe import subscribe

        subscribe(result_table, on_change)

    return queries, response_writer


def response_writer(*args: Any, **kwargs: Any) -> None:
    raise RuntimeError("use the response_writer returned by rest_connector")
