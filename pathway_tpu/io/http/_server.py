"""REST request/response connector — the production query-serving plane.

Mirrors the reference's ``python/pathway/io/http/_server.py`` (``PathwayWebserver``
aiohttp server ``:329``, ``rest_connector`` ``:624``, ``RestServerSubject`` ``:490``):
an HTTP request becomes a row in a streaming queries table (keyed by a request id);
the paired ``response_writer`` subscribes to a result table and resolves the stored
future for that id, completing the HTTP response. Queries are append-only ("as-of-now"
discipline) — results for a request are served once and not retracted.

r14 turns the single-loop shim into a serving tier:

- **Admission**: every route carries a bounded in-flight budget
  (``PATHWAY_SERVE_MAX_INFLIGHT``) and, with the flow plane on, checks its
  input's ``interactive``-class :class:`~pathway_tpu.flow.credit.IngestGate`
  for credit — overload is shed with a fast ``429`` + ``Retry-After`` and an
  exact counter instead of an unbounded futures dict.
- **Arrival-driven query ticks**: a request no longer waits out the fixed
  autocommit poll. Arrival schedules an engine tick through the runtime's
  :class:`~pathway_tpu.engine.runtime.TickWakeup` after a short coalesce
  window (``PATHWAY_SERVE_COALESCE_MS``, immediate once
  ``PATHWAY_SERVE_COALESCE_ROWS`` requests wait), so concurrent requests
  coalesce into ONE tick and ride the microbatch path together.
- **Vectorized responses**: the response writer collects the tick's emissions
  and resolves all of its futures in one pass per event loop
  (``on_time_end``), not one ``call_soon_threadsafe`` per row.
- **OpenAPI**: the route schemas (and the previously-ignored
  ``documentation`` param) generate an OpenAPI 3 document served at
  ``/_schema``.
- **Lifecycle**: ``PathwayWebserver.stop()`` awaits the aiohttp runner's
  cleanup and joins the server thread (back-to-back runs can reuse the
  port); engine shutdown flushes still-pending request futures with ``503``
  instead of leaving clients hanging for the request timeout.
"""

from __future__ import annotations

import asyncio
import itertools
import json as _json
import threading
import time as _time_mod
import weakref
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.json import Json
from pathway_tpu.internals.keys import splitmix64
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe

#: request future resolution values that are NOT payloads
_SHUTDOWN = object()  # engine stopped with the request still pending -> 503

#: client-facing request timeout (the engine answered nothing for this long)
_REQUEST_TIMEOUT_S = 120.0


def _jsonable(v: Any) -> Any:
    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


@dataclass
class EndpointDocumentation:
    """Human-facing route metadata woven into the generated OpenAPI document
    (reference ``_server.py`` EndpointDocumentation). Every field is optional;
    an undocumented route still appears in ``/_schema`` with its schema."""

    summary: str | None = None
    description: str | None = None
    tags: list[str] = field(default_factory=list)


# --------------------------------------------------------------------- serving


class _RouteServing:
    """Per-route serving state: the request futures, the admission budget and
    the exact counters ``/status``'s serving section reports."""

    def __init__(self, route: str, methods: tuple[str, ...], schema):
        from pathway_tpu.internals.parse_graph import G
        from pathway_tpu.observability.metrics import Histogram

        self.route = route
        self.methods = tuple(methods)
        self.schema = schema
        # hoisted once per route: the payload-parse helpers run per request
        # at every door, and schema dict materialization is not free there
        if schema is not None:
            self.schema_columns = schema.column_names()
            self.schema_dtypes = schema.dtypes()
            self.schema_defaults = schema.default_values()
        else:
            self.schema_columns, self.schema_dtypes, self.schema_defaults = (
                [],
                {},
                {},
            )
        self.lock = threading.Lock()
        self.node: ops.StreamInputNode | None = None
        self.runtime: Any = None
        #: graph generation this route was defined under — registries outlive
        #: graphs, so the fabric (and cleanup) must tell current from leftover
        self.graph_gen = G.generation
        #: the route's request_validator, exposed so fabric front doors on
        #: peer processes validate at ingress exactly like the owner's door
        self.request_validator: Any = None
        #: key -> (future, owning event loop, arrival time_ns, row values)
        self.futures: dict[int, tuple] = {}
        self.closed = True  # open between driver.start() and flush_pending()
        self.delete_completed = True
        # admission knobs, re-read per run in configure()
        self.max_inflight = 1024
        self.coalesce_s = 0.002
        self.coalesce_rows = 64
        self.tick_mode = "arrival"
        self.arrivals_since_wake = 0
        self._wake_window_t0 = 0.0
        # front-door protection (fabric/limits): per-route token bucket +
        # API-key guard, built in configure() from env or per-route overrides
        self.rate_limit_override: float | None = None
        self.api_keys_override: tuple[str, ...] | None = None
        self.limiter: Any = None
        self.auth: Any = None
        #: ingress-side forwarded requests currently awaiting the owner
        #: (fabric front doors; bounded by the same max_inflight budget)
        self.fwd_inflight = 0
        # counters (exact; the shed path is only acceptable because of them)
        self.requests_total = 0
        self.responses_total = 0
        self.shed_total = 0
        self.errors_total = 0  # 4xx validation/parse failures
        self.timeouts_total = 0
        self.limited_total = 0  # 429s from the token bucket
        self.unauthorized_total = 0  # 401s (no API key presented)
        self.forbidden_total = 0  # 403s (wrong API key)
        self.forwarded_out_total = 0  # ingress -> owner fabric forwards
        self.forwarded_in_total = 0  # owner side: requests arriving via fabric
        self.batches_total = 0  # response-resolution passes (~= serving ticks)
        self.batched_rows_total = 0  # responses resolved by those passes
        self.latency = Histogram()
        #: optional extra /status fields (serve_table attaches its replica
        #: store's rows/lag/seq here)
        self.extra_snapshot: Any = None

    # ---------------------------------------------------------------- lifecycle
    def configure(self) -> None:
        """Per-run admission/coalesce knobs (called by the connector driver's
        ``start`` so env changes between runs take effect)."""
        from pathway_tpu.internals.config import get_pathway_config

        from pathway_tpu.fabric.limits import ApiKeyGuard, TokenBucket

        cfg = get_pathway_config()
        self.max_inflight = cfg.serve_max_inflight
        self.coalesce_s = cfg.serve_coalesce_ms / 1000.0
        self.coalesce_rows = cfg.serve_coalesce_rows
        self.tick_mode = cfg.serve_tick
        rate = (
            self.rate_limit_override
            if self.rate_limit_override is not None
            else cfg.serve_rate
        )
        self.limiter = TokenBucket(rate, cfg.serve_burst or None) if rate > 0 else None
        keys = (
            self.api_keys_override
            if self.api_keys_override is not None
            else cfg.serve_api_keys
        )
        self.auth = ApiKeyGuard(keys) if keys else None
        self.closed = False

    def flush_pending(self) -> int:
        """Engine shutdown: resolve every still-pending request future with
        the shutdown sentinel so handlers answer ``503`` now instead of
        timing out after ``_REQUEST_TIMEOUT_S``. Returns how many flushed."""
        with self.lock:
            self.closed = True
            pending, self.futures = self.futures, {}
        from pathway_tpu.observability import requests as _req_trace

        rp = _req_trace.current()
        if rp is not None:
            for key in pending:
                rp.drop(key)
        by_loop: dict[Any, list] = {}
        for fut, loop, _arrival_ns, _values in pending.values():
            by_loop.setdefault(loop, []).append((fut, _SHUTDOWN))
        for loop, items in by_loop.items():
            try:
                loop.call_soon_threadsafe(_set_results, items)
            except RuntimeError:
                pass  # loop already closed; the client connection is gone too
        return len(pending)

    # ---------------------------------------------------------------- admission
    def try_admit(self) -> str | None:
        """Admission check at request arrival: returns a shed reason, or None
        when the request may proceed to parsing. The in-flight budget bounds
        the futures dict; the flow plane's credit is taken atomically at push
        time (:meth:`push_admitted`)."""
        with self.lock:
            if self.closed:
                return "shutting_down"
            if len(self.futures) + self.fwd_inflight >= self.max_inflight:
                return "max_inflight"
        return None

    def push_admitted(self, key: int, values: tuple) -> bool:
        """Push one admitted query row into the engine. With the flow plane
        on, the route input's ``interactive``-class :class:`IngestGate`
        credit is taken NON-BLOCKINGLY first — a saturated pod sheds here
        (fast, counted, explicit 429) rather than silently dropping a row
        whose response future is already registered, or stalling the shared
        aiohttp event loop on the blocking credit path. The append itself
        bypasses ``push``'s gating (the credit is already ours)."""
        node = self.node
        assert node is not None, "rest_connector: engine not running"
        gate = getattr(node, "flow_gate", None)
        if gate is not None and not gate.try_admit(1):
            return False
        node._append_events([(key, values, 1)])
        return True

    def schedule_tick(self) -> None:
        """Arrival-driven tick scheduling with coalescing: the first arrival
        arms a wakeup ``coalesce_s`` out so concurrent requests share one
        engine tick; a full coalesce bucket wakes the loop immediately."""
        if self.tick_mode != "arrival":
            return
        wakeup = getattr(self.runtime, "wakeup", None)
        if wakeup is None:
            return
        now = _time_mod.monotonic()
        with self.lock:
            # the count is scoped to ONE coalesce window: arrivals older than
            # the window were drained by an intervening tick, so carrying
            # them over would eventually force every arrival to wake the
            # loop immediately and defeat coalescing
            if now - self._wake_window_t0 > self.coalesce_s:
                self.arrivals_since_wake = 0
                self._wake_window_t0 = now
            self.arrivals_since_wake += 1
            immediate = self.arrivals_since_wake >= self.coalesce_rows
            if immediate:
                self.arrivals_since_wake = 0
                self._wake_window_t0 = now
        delay = 0.0 if immediate else self.coalesce_s
        nudge = getattr(self.runtime, "coord_nudge", None)
        if nudge is not None:
            # zero-hop peer door: the coordinator (pid 0) owns the inter-tick
            # sleep, so this process's arrivals wake it over the fabric
            nudge(delay)
        else:
            wakeup.request(delay)

    # ---------------------------------------------------------------- telemetry
    def snapshot(self) -> dict[str, Any]:
        from pathway_tpu.observability.metrics import Histogram

        snap = self.latency.snapshot()

        def _q(q):
            v = Histogram.quantile(snap, q)
            return None if v is None or v == float("inf") else v

        with self.lock:
            inflight = len(self.futures) + self.fwd_inflight
        return {
            "route": self.route,
            "methods": list(self.methods),
            "in_flight": inflight,
            "max_inflight": self.max_inflight,
            "requests_total": self.requests_total,
            "responses_total": self.responses_total,
            "shed_total": self.shed_total,
            "errors_total": self.errors_total,
            "timeouts_total": self.timeouts_total,
            "limited_total": self.limited_total,
            "unauthorized_total": self.unauthorized_total,
            "forbidden_total": self.forbidden_total,
            "forwarded_out_total": self.forwarded_out_total,
            "forwarded_in_total": self.forwarded_in_total,
            "rate_limit": self.limiter.rate if self.limiter is not None else None,
            "auth": self.auth is not None,
            "batches_total": self.batches_total,
            "mean_batch": round(
                self.batched_rows_total / self.batches_total, 2
            )
            if self.batches_total
            else None,
            "latency_p50_s": _q(0.5),
            "latency_p99_s": _q(0.99),
            "tick_mode": self.tick_mode,
        }


def _set_results(items: list[tuple]) -> None:
    """One event-loop callback resolving a whole tick's futures (the
    vectorized response pass — was one ``call_soon_threadsafe`` per row)."""
    for fut, value in items:
        if not fut.done():
            fut.set_result(value)


#: every constructed route's serving state; weak so finished graphs release
#: their routes (the monitoring plane filters by the queried runtime)
_ROUTES: "weakref.WeakSet[_RouteServing]" = weakref.WeakSet()

#: every constructed webserver; the fabric plane walks this to build peer
#: front doors mirroring each server's route table (weak: finished graphs
#: release their servers)
_WEBSERVERS: "weakref.WeakSet[PathwayWebserver]" = weakref.WeakSet()

#: process-wide request-key mint shared by every route: a route-local counter
#: would hand the Nth request of two routes the SAME engine key — and the
#: request-trace plane keys its live table (and mints request/trace ids) by
#: that raw key, so colliding keys would cross-wire two requests' flights
_KEY_SEQ = itertools.count(1)


def mint_request_key() -> int:
    """Process-unique engine key for one admitted request. The sequence is
    salted with the process id BEFORE hashing: with the fabric on, every
    process's front door mints keys, and two processes' Nth requests must
    never collide (the request id — and so the derived trace id — IS the
    key). Process 0 hashes the bare sequence, so single-door runs mint the
    exact pre-fabric keys."""
    from pathway_tpu.internals.config import get_pathway_config

    salted = (get_pathway_config().process_id << 48) ^ next(_KEY_SEQ)
    return int(splitmix64(np.asarray([salted], dtype=np.uint64))[0])


def mint_local_key(state: "_RouteServing") -> int:
    """Engine key for one admitted request, constrained (under the shard map)
    to a key THIS process owns. Zero-hop serving hinges on this: the request
    row, its engine work, the subscribe callback and the response future must
    all live on the door that accepted the request, and the shard map routes
    rows by key — so the door rejection-samples the mint until the key's
    owner is itself. Expected tries = n_processes (geometric); the 4096-try
    bound exists only to turn a corrupted map into a loud error. Without a
    shard map this is exactly :func:`mint_request_key`."""
    rt = state.runtime
    sm = getattr(rt, "shardmap", None)
    if sm is None:
        return mint_request_key()
    pid = int(getattr(rt, "pid", 0))
    threads = max(1, int(getattr(rt, "threads", 1)))
    for _ in range(4096):
        key = mint_request_key()
        owner = int(sm.owner_of_keys(np.asarray([key], dtype=np.uint64))[0])
        if owner // threads == pid:
            return key
    raise RuntimeError(
        "shardmap: could not mint a locally-owned request key "
        f"(pid={pid}, map v{sm.version})"
    )


def _zerohop_owner_headers() -> dict | None:
    """``X-Pathway-Fabric: owner:p<pid>`` when the shard-map fabric is live.

    Under zero-hop routing EVERY door — the coordinator's original webserver
    included, which never passes through a fabric door wrapper — answers as
    the owner of the key it minted, and the header must say so truthfully on
    all of them."""
    from pathway_tpu import fabric as _fabric

    plane = _fabric._plane
    if plane is not None and plane.shardmap is not None:
        return {"X-Pathway-Fabric": f"owner:p{plane.pid}"}
    return None


def _door_event(state: "_RouteServing", reason: str) -> None:
    """Trace/request-plane breadcrumbs for a request rejected at the door."""
    from pathway_tpu import observability as _obs
    from pathway_tpu.observability import requests as _req_trace

    tracer = _obs.current()
    if tracer is not None:
        tracer.event(
            "serve/shed", {"pathway.route": state.route, "pathway.reason": reason}
        )
    rp = _req_trace.current()
    if rp is not None:
        rp.note_shed(state.route, reason)


def gate_check(
    state: "_RouteServing", headers: Any
) -> tuple[int, dict, dict[str, str]] | None:
    """Front-door protection shared by EVERY door serving this route — the
    coordinator's aiohttp handler and each fabric peer door: API-key auth
    (401 no key / 403 wrong key), then the per-route token bucket (429 with
    an exact Retry-After). Returns ``(status, body, headers)`` on rejection,
    else None. Runs before admission and before the body is read, so a
    hostile flood costs one header inspection per request. Counters are
    exact per process and merged pod-wide over the heartbeat telemetry."""
    from pathway_tpu.fabric import limits as _limits

    auth = state.auth
    if auth is not None:
        verdict = auth.check(_limits.extract_api_key(headers))
        if verdict == _limits.UNAUTHORIZED:
            state.unauthorized_total += 1
            _door_event(state, "unauthorized")
            return 401, {"error": "missing api key"}, {}
        if verdict == _limits.FORBIDDEN:
            state.forbidden_total += 1
            _door_event(state, "forbidden")
            return 403, {"error": "invalid api key"}, {}
    limiter = state.limiter
    if limiter is not None:
        wait = limiter.try_take()
        if wait > 0.0:
            state.limited_total += 1
            _door_event(state, "rate_limited")
            return (
                429,
                {"error": "rate limited", "reason": "rate_limit"},
                {"Retry-After": _limits.retry_after_header(wait)},
            )
    return None


async def extract_payload(state: "_RouteServing", request: Any) -> dict:
    """Request → payload dict, identically at every door (GET params coerced
    to schema dtypes, POST bodies parsed as JSON with a raw-text fallback) —
    the fabric forwards parsed VALUES, so ingress parsing must match the
    owner's byte for byte."""
    dtypes = state.schema_dtypes
    if request.method == "GET":
        # keep EVERY query param (request_validator may inspect extras);
        # coerce only the schema-typed ones
        return {
            k: _coerce(v, dtypes[k]) if k in dtypes else v
            for k, v in request.rel_url.query.items()
        }
    try:
        return await request.json()
    except Exception:
        return {"query": await request.text()}


def build_row_values(state: "_RouteServing", payload: dict) -> tuple:
    """Payload dict → the schema-ordered values tuple pushed into the engine
    (defaults applied, JSON columns boxed) — shared by the aiohttp handler
    and fabric ingress doors."""
    columns = state.schema_columns
    dtypes = state.schema_dtypes
    defaults = state.schema_defaults
    values = []
    for c in columns:
        v = payload.get(c, defaults.get(c))
        d = dt.unoptionalize(dtypes[c])
        if d == dt.JSON and v is not None and not isinstance(v, Json):
            v = Json(v)
        values.append(v)
    return tuple(values)


#: the per-route counter block piggybacked on heartbeats and rolled up
#: pod-wide (exact sheds/auth failures are the contract of shedding at all)
_COMPACT_FIELDS = (
    ("requests", "requests_total"),
    ("responses", "responses_total"),
    ("shed", "shed_total"),
    ("limited", "limited_total"),
    ("unauthorized", "unauthorized_total"),
    ("forbidden", "forbidden_total"),
    ("errors", "errors_total"),
    ("timeouts", "timeouts_total"),
    ("forwarded_out", "forwarded_out_total"),
    ("forwarded_in", "forwarded_in_total"),
)


def _compact_counters(rs: "_RouteServing") -> dict[str, int]:
    return {name: getattr(rs, attr) for name, attr in _COMPACT_FIELDS}


def serving_heartbeat_summary(runtime) -> dict[str, dict] | None:
    """route → compact counters for this process's live doors — rides the
    heartbeat telemetry block so the coordinator's /status can roll serving
    up cluster-wide (fabric peers count their own ingress traffic)."""
    routes = {
        rs.route: _compact_counters(rs)
        for rs in list(_ROUTES)
        if rs.runtime is runtime
    }
    return routes or None


def serving_status(runtime) -> dict[str, Any] | None:
    """The ``/status`` serving section for one runtime's live routes, or None
    when the run serves nothing. On a cluster coordinator with fabric peers
    reporting, a ``cluster`` block adds the pod-wide per-route rollup."""
    local = [rs for rs in list(_ROUTES) if rs.runtime is runtime]
    rows = sorted((rs.snapshot() for rs in local), key=lambda r: r["route"])
    if not rows:
        return None
    out = {
        "routes": rows,
        "requests_total": sum(r["requests_total"] for r in rows),
        "responses_total": sum(r["responses_total"] for r in rows),
        "shed_total": sum(r["shed_total"] for r in rows),
    }
    monitor = getattr(runtime, "hb_monitor", None)
    peers = monitor.peer_serving() if hasattr(monitor, "peer_serving") else {}
    if peers:
        merged: dict[str, dict[str, int]] = {
            rs.route: _compact_counters(rs) for rs in local
        }
        for summary in peers.values():
            for route, counters in (summary or {}).items():
                agg = merged.setdefault(
                    route, {name: 0 for name, _ in _COMPACT_FIELDS}
                )
                for name, _attr in _COMPACT_FIELDS:
                    agg[name] = agg.get(name, 0) + int(counters.get(name, 0))
        out["cluster"] = {
            "n_reporting": 1 + len(peers),
            "routes": {r: merged[r] for r in sorted(merged)},
        }
    return out


def serving_prometheus_lines(runtime) -> list[str]:
    """``pathway_serve_*`` exposition lines for ``/metrics``."""
    from pathway_tpu.internals.monitoring import escape_label_value
    from pathway_tpu.observability.metrics import BUCKET_BOUNDS_S

    routes = [rs for rs in list(_ROUTES) if rs.runtime is runtime]
    if not routes:
        return []
    routes.sort(key=lambda r: r.route)
    lines: list[str] = []
    counters = (
        ("pathway_serve_requests_total", "Requests received by a REST route", "requests_total", "counter"),
        ("pathway_serve_responses_total", "Responses served by a REST route", "responses_total", "counter"),
        ("pathway_serve_shed_total", "Requests shed (429) by a REST route's admission", "shed_total", "counter"),
        ("pathway_serve_errors_total", "Requests rejected (4xx) by a REST route", "errors_total", "counter"),
        ("pathway_serve_limited_total", "Requests shed (429) by a REST route's token bucket", "limited_total", "counter"),
        ("pathway_serve_unauthorized_total", "Requests rejected 401 (no API key) by a REST route", "unauthorized_total", "counter"),
        ("pathway_serve_forbidden_total", "Requests rejected 403 (wrong API key) by a REST route", "forbidden_total", "counter"),
        ("pathway_serve_forwarded_total", "Requests this door forwarded to the owning process over the fabric", "forwarded_out_total", "counter"),
        ("pathway_serve_inflight", "Requests admitted but not yet answered", None, "gauge"),
    )
    for name, help_text, attr, mtype in counters:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for rs in routes:
            label = f'route="{escape_label_value(rs.route)}"'
            value = (
                len(rs.futures) + rs.fwd_inflight
                if attr is None
                else getattr(rs, attr)
            )
            lines.append(f"{name}{{{label}}} {value}")
    lines.append("# HELP pathway_serve_latency_seconds Arrival-to-response latency per REST route")
    lines.append("# TYPE pathway_serve_latency_seconds histogram")
    for rs in routes:
        label = f'route="{escape_label_value(rs.route)}"'
        snap = rs.latency.snapshot()
        cum = 0
        for bound, c in zip(BUCKET_BOUNDS_S, snap["counts"]):
            cum += c
            lines.append(
                f'pathway_serve_latency_seconds_bucket{{{label},le="{bound!r}"}} {cum}'
            )
        cum += snap["counts"][-1]
        lines.append(
            f'pathway_serve_latency_seconds_bucket{{{label},le="+Inf"}} {cum}'
        )
        lines.append(f"pathway_serve_latency_seconds_sum{{{label}}} {snap['sum_s']}")
        lines.append(f"pathway_serve_latency_seconds_count{{{label}}} {snap['count']}")
    return lines


# --------------------------------------------------------------------- OpenAPI


def _openapi_type(d: dt.DType) -> dict[str, Any]:
    base = dt.unoptionalize(d)
    if base == dt.INT:
        return {"type": "integer"}
    if base == dt.FLOAT:
        return {"type": "number"}
    if base == dt.BOOL:
        return {"type": "boolean"}
    if base == dt.STR:
        return {"type": "string"}
    if base == dt.JSON:
        return {}  # any JSON value
    return {}


def openapi_spec(webserver: "PathwayWebserver") -> dict[str, Any]:
    """OpenAPI 3 document generated from the registered routes' Pathway
    schemas + ``documentation`` metadata (served at ``/_schema``)."""
    paths: dict[str, dict] = {}
    for route, methods, _handler, meta in webserver._routes:
        if meta is None:
            continue
        schema = meta.get("schema")
        doc = meta.get("documentation")
        props: dict[str, Any] = {}
        required: list[str] = []
        if schema is not None:
            for name, cdef in schema.columns().items():
                spec = _openapi_type(cdef.dtype)
                if cdef.has_default and cdef.default_value is not None:
                    spec = {**spec, "default": _jsonable(cdef.default_value)}
                props[name] = spec
                if not cdef.has_default and not isinstance(cdef.dtype, dt.Optional):
                    required.append(name)
        body_schema: dict[str, Any] = {"type": "object", "properties": props}
        if required:
            body_schema["required"] = required
        responses = {
            "200": {
                "description": "query answered as-of-now",
                "content": {"application/json": {"schema": {}}},
            },
            "400": {"description": "malformed payload or request_validator rejection"},
            "429": {
                "description": "admission shed (in-flight budget or ingest credit exhausted); retry after the Retry-After seconds",
            },
            "503": {"description": "engine shutting down; request not processed"},
            "504": {"description": "engine produced no answer within the request timeout"},
        }
        item: dict[str, Any] = {}
        for m in methods:
            op: dict[str, Any] = {
                "operationId": f"{m.lower()}_{route.strip('/').replace('/', '_') or 'root'}",
                "responses": responses,
            }
            if doc is not None:
                summary = getattr(doc, "summary", None) or (
                    doc.get("summary") if isinstance(doc, dict) else None
                )
                description = getattr(doc, "description", None) or (
                    doc.get("description") if isinstance(doc, dict) else None
                )
                tags = getattr(doc, "tags", None) or (
                    doc.get("tags") if isinstance(doc, dict) else None
                )
                if summary:
                    op["summary"] = summary
                if description:
                    op["description"] = description
                if tags:
                    op["tags"] = list(tags)
            if m.upper() == "GET":
                op["parameters"] = [
                    {
                        "name": name,
                        "in": "query",
                        "required": name in required,
                        "schema": spec,
                    }
                    for name, spec in props.items()
                ]
            else:
                op["requestBody"] = {
                    "required": bool(required),
                    "content": {"application/json": {"schema": body_schema}},
                }
            item[m.lower()] = op
        paths[route] = item
    return {
        "openapi": "3.0.3",
        "info": {"title": "pathway_tpu serving plane", "version": "1"},
        "paths": paths,
    }


# ------------------------------------------------------------------- webserver


class PathwayWebserver:
    """One aiohttp server shared by many rest_connector routes
    (reference ``_server.py:329``). ``stop()`` is synchronous and complete:
    it flushes pending request futures, awaits the runner's cleanup on the
    server loop and joins the thread — the port is free when it returns, so
    two back-to-back runs can bind the same address."""

    def __init__(self, host: str = "0.0.0.0", port: int = 8080, with_cors: bool = False):
        self.host = host
        self.port = port
        self.with_cors = with_cors
        _WEBSERVERS.add(self)
        #: (route, methods, handler, meta) — meta carries schema/documentation
        #: for OpenAPI generation and the serving state for lifecycle flushes
        self._routes: list[tuple[str, list[str], Any, dict | None]] = []
        self._started = threading.Event()
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._runner = None
        self._start_error: BaseException | None = None

    def _add_route(
        self, route: str, methods: list[str], handler: Any, meta: dict | None = None
    ) -> None:
        self._routes.append((route, methods, handler, meta))

    def _route_states(self) -> list[_RouteServing]:
        return [
            m["serving"]
            for _r, _m, _h, m in self._routes
            if m is not None and m.get("serving") is not None
        ]

    def start(self) -> None:
        if self._thread is not None:
            return

        import aiohttp.web as web

        app = web.Application()
        for route, methods, handler, _meta in self._routes:
            for m in methods:
                app.router.add_route(m, route, handler)

        async def schema_handler(_request: "web.Request") -> "web.Response":
            return web.json_response(openapi_spec(self))

        app.router.add_route("GET", "/_schema", schema_handler)

        # every door serves liveness/readiness from the health plane's door
        # state machine (unconditional 200s when the plane is off) — the
        # contract a load balancer probes; user routes win a name collision
        taken = {r for r, _m, _h, _meta in self._routes}

        async def healthz_handler(_request: "web.Request") -> "web.Response":
            from pathway_tpu.observability import health as _health

            status, doc = _health.healthz_payload()
            return web.json_response(doc, status=status)

        async def readyz_handler(_request: "web.Request") -> "web.Response":
            from pathway_tpu.observability import health as _health

            status, doc, headers = _health.readyz_payload()
            return web.json_response(doc, status=status, headers=headers or None)

        if "/healthz" not in taken:
            app.router.add_route("GET", "/healthz", healthz_handler)
        if "/readyz" not in taken:
            app.router.add_route("GET", "/readyz", readyz_handler)

        self._started.clear()
        self._start_error = None

        def serve() -> None:
            loop = asyncio.new_event_loop()
            asyncio.set_event_loop(loop)
            self._loop = loop
            # handler_cancellation: a disconnected client cancels its handler
            # (aiohttp >= 3.9 defaults this OFF), so dead requests release
            # their in-flight budget + request-trace record immediately
            # instead of holding both until the 120 s timeout — the handler's
            # CancelledError branch owns the cleanup
            runner = web.AppRunner(app, handler_cancellation=True)
            try:
                loop.run_until_complete(runner.setup())
                site = web.TCPSite(runner, self.host, self.port)
                loop.run_until_complete(site.start())
            except BaseException as e:  # bind failure -> surface in start()
                self._start_error = e
                self._started.set()
                loop.close()
                return
            self._runner = runner
            self._started.set()
            loop.run_forever()
            # stop() already awaited runner.cleanup() via the loop; anything
            # else scheduled is drained by closing
            loop.close()

        self._thread = threading.Thread(target=serve, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)
        if self._start_error is not None:
            err, self._start_error = self._start_error, None
            self._thread = None
            self._loop = None
            raise RuntimeError(
                f"PathwayWebserver failed to bind {self.host}:{self.port}: {err!r}"
            ) from err

    def stop(self) -> None:
        thread, loop = self._thread, self._loop
        if thread is None or loop is None:
            return
        # unblock waiting clients first: their handlers answer 503 while the
        # server is still accepting writes
        for rs in self._route_states():
            rs.flush_pending()
        runner = self._runner
        if runner is not None:
            try:
                # graceful: waits for in-flight handlers, closes the site
                # sockets — the port is released here, not at thread death
                asyncio.run_coroutine_threadsafe(
                    runner.cleanup(), loop
                ).result(timeout=10)
            except Exception:
                pass
        try:
            loop.call_soon_threadsafe(loop.stop)
        except RuntimeError:
            pass
        thread.join(timeout=10)
        self._thread = None
        self._loop = None
        self._runner = None
        self._started.clear()


class _RestDriver:
    """Connector driver: the server lives for the duration of the run."""

    virtual = False

    def __init__(self, webserver: PathwayWebserver, state: _RouteServing):
        self.webserver = webserver
        self.state = state

    def start(self) -> None:
        self.state.configure()
        self.webserver.start()

    def is_finished(self) -> bool:
        return False  # unbounded; stopped via runtime.request_stop()

    def stop(self) -> None:
        # flush BEFORE the server goes down so every pending client gets a
        # fast 503 through a still-open connection, then release the port
        self.state.flush_pending()
        self.webserver.stop()


# --------------------------------------------------------------- rest_connector


def _coerce(v: Any, d: dt.DType) -> Any:
    """GET query params arrive as strings; coerce to the schema dtype the
    POST/JSON path would have produced."""
    base = dt.unoptionalize(d)
    if v is None or not isinstance(v, str) or base == dt.STR:
        return v
    try:
        if base == dt.INT:
            return int(v)
        if base == dt.FLOAT:
            return float(v)
        if base == dt.BOOL:
            return v.strip().lower() not in ("", "0", "false", "no")
        if base == dt.JSON:
            return _json.loads(v)
    except (ValueError, TypeError):
        return v  # schema validation downstream reports it
    return v


def rest_connector(
    host: str = "0.0.0.0",
    port: int = 8080,
    *,
    webserver: PathwayWebserver | None = None,
    route: str = "/",
    schema: schema_mod.SchemaMetaclass | None = None,
    methods: tuple[str, ...] = ("POST",),
    autocommit_duration_ms: int | None = 20,
    keep_queries: bool = False,
    delete_completed_queries: bool | None = None,
    request_validator: Any = None,
    documentation: Any = None,
    rate_limit: float | None = None,
    api_keys: Any = None,
) -> tuple[Table, Any]:
    """Returns ``(queries_table, response_writer)``.

    ``delete_completed_queries`` / ``keep_queries``: once a query's response
    is served, its row is retracted from the queries table (so downstream
    state doesn't grow with request history) unless ``keep_queries=True``;
    an explicit ``delete_completed_queries`` wins over ``keep_queries``.

    ``rate_limit`` / ``api_keys`` override the ``PATHWAY_SERVE_RATE`` /
    ``PATHWAY_SERVE_API_KEYS`` front-door protection for THIS route
    (``rate_limit=0`` disables the bucket, ``api_keys=()`` disables auth);
    both apply at every door serving the route, fabric peers included.
    """
    ws = webserver or PathwayWebserver(host=host, port=port)
    if schema is None:
        schema = schema_mod.schema_from_types(query=str)
    columns = schema.column_names()
    np_dtypes = schema.np_dtypes()
    state = _RouteServing(route, methods, schema)
    state.delete_completed = (
        delete_completed_queries
        if delete_completed_queries is not None
        else not keep_queries
    )
    state.request_validator = request_validator
    if rate_limit is not None:
        state.rate_limit_override = float(rate_limit)
    if api_keys is not None:
        state.api_keys_override = tuple(api_keys)
    _ROUTES.add(state)

    import aiohttp.web as web

    def _shed_response(reason: str):
        state.shed_total += 1
        from pathway_tpu import observability as _obs
        from pathway_tpu.observability import requests as _req_trace

        tracer = _obs.current()
        if tracer is not None:
            tracer.event(
                "serve/shed", {"pathway.route": route, "pathway.reason": reason}
            )
        rp = _req_trace.current()
        if rp is not None:
            rp.note_shed(route, reason)
        status = 503 if reason == "shutting_down" else 429
        return web.json_response(
            {"error": "overloaded", "reason": reason},
            status=status,
            headers={"Retry-After": "1"},
        )

    async def handler(request: "web.Request") -> "web.Response":
        from pathway_tpu.observability import health as _health

        hp = _health.current()
        if hp is not None and request.headers.get("X-Pathway-Canary"):
            # synthetic self-probe: answer from the door state machine and
            # return BEFORE any user-facing counter or engine work — canaries
            # must never show up as traffic
            status, doc = hp.canary_response(route)
            return web.json_response(doc, status=status)
        state.requests_total += 1
        gated = gate_check(state, request.headers)
        if gated is not None:
            status, body, hdrs = gated
            return web.json_response(body, status=status, headers=hdrs or None)
        shed = state.try_admit()
        if shed is not None:
            return _shed_response(shed)
        payload = await extract_payload(state, request)
        if request_validator is not None:
            try:
                request_validator(payload)
            except Exception as e:
                state.errors_total += 1
                return web.json_response({"error": str(e)}, status=400)
        values = build_row_values(state, payload)
        arrival_ns = _time_mod.time_ns()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        loop = fut.get_loop()
        with state.lock:
            if state.closed:
                return _shed_response("shutting_down")
            if len(state.futures) >= state.max_inflight:
                # re-check under the registration lock: the arrival-time check
                # ran BEFORE awaiting the request body, and any number of
                # handlers can suspend there — the budget must bind where the
                # futures dict actually grows
                return _shed_response("max_inflight")
            key = mint_local_key(state)
            state.futures[key] = (fut, loop, arrival_ns, values)
        # request-scoped tracing: the admitted query row's engine key IS the
        # request id (it rides the dataflow and the cluster wire for free).
        # Registration happens BEFORE the push makes the row visible to the
        # engine — a fast tick could otherwise resolve (and try to complete)
        # the request before begin() ran, leaking it in the live table
        from pathway_tpu.observability import requests as _req_trace

        rp = _req_trace.current()
        request_id = rp.begin(key, route, arrival_ns) if rp is not None else None
        rid_headers = (
            {"X-Pathway-Request-Id": request_id} if request_id is not None else None
        )
        fabric_headers = _zerohop_owner_headers()
        if fabric_headers is not None:
            rid_headers = {**(rid_headers or {}), **fabric_headers}
        if not state.push_admitted(key, values):
            with state.lock:
                state.futures.pop(key, None)
            if rp is not None:
                rp.drop(key)  # never reached the engine; no flight to trace
            return _shed_response("no_ingest_credit")
        state.schedule_tick()
        try:
            result = await asyncio.wait_for(fut, timeout=_REQUEST_TIMEOUT_S)
        except asyncio.CancelledError:
            # client disconnected: aiohttp cancels the handler task — the one
            # exit where neither the response side nor the timeout branch
            # runs, which would leak the in-flight record (pinning plane.hot)
            # and the query row. Clean up like a timeout; futures.pop is the
            # ownership token (ent None = the response side won the race and
            # owns the retraction + completion)
            with state.lock:
                ent = state.futures.pop(key, None)
            if ent is not None:
                if rp is not None:
                    rp.complete(key, "cancelled")
                if state.delete_completed and state.node is not None:
                    state.node._append_events([(key, values, -1)])
                    state.schedule_tick()
            raise
        except asyncio.TimeoutError:
            with state.lock:
                ent = state.futures.pop(key, None)
            state.timeouts_total += 1
            if rp is not None:
                # a timed-out request is exactly what tail sampling exists
                # for — its flight path is kept unconditionally
                rp.complete(key, "timeout")
            # ent None = the response side won the race and already owns the
            # retraction; retracting again would push an unpaired -1
            if ent is not None and state.delete_completed and state.node is not None:
                # nobody is waiting anymore: retract the query row so the
                # engine doesn't keep dead-request state forever (the normal
                # retraction happens at response time, which never came)
                state.node._append_events([(key, values, -1)])
                state.schedule_tick()
            return web.json_response(
                {"error": "timeout"}, status=504, headers=rid_headers
            )
        if result is _SHUTDOWN:
            if rp is not None:
                rp.drop(key)  # no flight to decompose; the client got a 503
            return web.json_response(
                {"error": "engine shutting down"}, status=503, headers=rid_headers
            )
        return web.json_response(_jsonable(result), headers=rid_headers)

    ws._add_route(
        route,
        list(methods),
        handler,
        meta={"schema": schema, "documentation": documentation, "serving": state},
    )

    def factory() -> Node:
        from pathway_tpu.internals.config import get_pathway_config

        node = ops.StreamInputNode(columns, np_dtypes)
        node.input_name = f"rest:{route}"
        if get_pathway_config().shardmap == "on":
            # zero-hop serving: every fabric door pushes into its own copy of
            # this node; keyed exchange keeps each request on its minting door
            node.fabric_ingest = True
        state.node = node
        return node

    def hook(node: Node, runtime: Any) -> None:
        if runtime is not None:
            state.runtime = runtime
            runtime.register_connector(_RestDriver(ws, state))

    lnode = LogicalNode(factory, [], name=f"rest:{route}", runtime_hook=hook)
    queries = Table(lnode, schema, Universe())

    def response_writer(result_table: Table) -> None:
        cols = result_table.column_names()
        collected: list[tuple[int, dict]] = []

        def on_change(key: int, row: dict, time: int, is_addition: bool) -> None:
            if is_addition:
                collected.append((int(key), row))

        def on_time_end(time: int) -> None:
            if not collected:
                return
            batch = collected[:]
            collected.clear()
            now_ns = _time_mod.time_ns()
            resolved: list[tuple[tuple, int, dict]] = []
            with state.lock:
                for key, row in batch:
                    ent = state.futures.pop(key, None)
                    if ent is not None:
                        resolved.append((ent, key, row))
                state.arrivals_since_wake = 0
            if not resolved:
                return
            # one vectorized resolution pass per event loop, not a
            # call_soon_threadsafe per row
            by_loop: dict[Any, list] = {}
            oldest_ns = now_ns
            retracts: list[tuple[int, tuple, int]] = []
            for (fut, loop, arrival_ns, values), key, row in resolved:
                value = (
                    row["result"] if "result" in row and len(cols) <= 2 else row
                )
                by_loop.setdefault(loop, []).append((fut, value))
                state.latency.observe((now_ns - arrival_ns) / 1e9)
                oldest_ns = min(oldest_ns, arrival_ns)
                if state.delete_completed:
                    retracts.append((key, values, -1))
            for loop, items in by_loop.items():
                try:
                    loop.call_soon_threadsafe(_set_results, items)
                except RuntimeError:
                    pass  # server stopping; flush_pending owns these clients
            state.responses_total += len(resolved)
            state.batches_total += 1
            state.batched_rows_total += len(resolved)
            from pathway_tpu import observability as _obs
            from pathway_tpu.observability import requests as _req_trace

            rp = _req_trace.current()
            if rp is not None:
                # completion runs the tail-based keep decision per request;
                # the respond span covers this resolution pass
                done_ns = _time_mod.time_ns()
                for _ent, key, _row in resolved:
                    rp.complete(key, "ok", now_ns, done_ns)
            tracer = _obs.current()
            if tracer is not None:
                tracer.span(
                    "serve/respond",
                    oldest_ns,
                    now_ns,
                    {
                        "pathway.route": route,
                        "pathway.responses": len(resolved),
                        "pathway.tick": time,
                    },
                )
            if retracts and state.node is not None:
                # retract served query rows (delete_completed_queries): this
                # is the server's own bookkeeping, bounded by the in-flight
                # budget, pushed from the engine thread — it must NOT take
                # the ingest credit path (admit_retract could wait on credits
                # that only replenish when THIS tick finishes: deadlock), so
                # it appends directly; the rows drain on the next tick
                state.node._append_events(retracts)

        from pathway_tpu.internals.config import get_pathway_config
        from pathway_tpu.io._subscribe import subscribe

        # zero-hop serving: under the shard map, each response row must fire
        # the callback on the process holding its request future — i.e. the
        # door that minted the (locally-owned) key — so route by row key
        route_by = (
            (lambda batch: batch.keys)
            if get_pathway_config().shardmap == "on"
            else None
        )
        subscribe(result_table, on_change, on_time_end=on_time_end, route_by=route_by)

    return queries, response_writer


def response_writer(*args: Any, **kwargs: Any) -> None:
    raise RuntimeError("use the response_writer returned by rest_connector")
