"""MinIO connector (reference ``python/pathway/io/minio``): S3-compatible —
the S3 connector with endpoint-style settings."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3
from pathway_tpu.io.s3 import AwsS3Settings


class MinIOSettings:
    def __init__(
        self,
        endpoint: str | None = None,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        with_path_style: bool = True,
        *,
        client: Any = None,
    ):
        self.endpoint = endpoint
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.with_path_style = with_path_style
        self.client = client

    def create_aws_settings(self) -> AwsS3Settings:
        return AwsS3Settings(
            bucket_name=self.bucket_name,
            access_key=self.access_key,
            secret_access_key=self.secret_access_key,
            endpoint=self.endpoint,
            with_path_style=self.with_path_style,
            client=self.client,
        )


def read(path: str, minio_settings: MinIOSettings | Any = None, **kwargs: Any):
    settings = (
        minio_settings.create_aws_settings()
        if isinstance(minio_settings, MinIOSettings)
        else minio_settings
    )
    return _s3.read(path, settings, **kwargs)


def write(table, path: str, minio_settings: MinIOSettings | Any = None, **kwargs: Any):
    settings = (
        minio_settings.create_aws_settings()
        if isinstance(minio_settings, MinIOSettings)
        else minio_settings
    )
    return _s3.write(table, path, settings, **kwargs)
