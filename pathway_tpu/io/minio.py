"""Gated connector: reference `python/pathway/io/minio`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("minio", "boto3 (S3-compatible object-store access)")
write = gate("minio", "boto3 (S3-compatible object-store access)")
