"""Gated connector: reference `python/pathway/io/logstash`. See _gated.py."""

from pathway_tpu.io._gated import gate

write = gate("logstash", "a reachable Logstash HTTP endpoint")
