"""Logstash writer (reference ``python/pathway/io/logstash``): POST every
diff row as a flat JSON object — with ``time``/``diff`` fields — to Logstash's
HTTP input plugin. Pure stdlib (urllib), so no dependency gate."""

from __future__ import annotations

import time as _time
import urllib.request
from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table


class RetryPolicy:
    """Fixed/backoff retry delays (reference ``io/http`` RetryPolicy shape)."""

    def __init__(self, first_delay_ms: int = 200, backoff_factor: float = 2.0):
        self.first_delay_ms = first_delay_ms
        self.backoff_factor = backoff_factor

    @classmethod
    def default(cls) -> "RetryPolicy":
        return cls()


def write(
    table: Table,
    endpoint: str,
    n_retries: int = 0,
    retry_policy: RetryPolicy | None = None,
    connect_timeout_ms: int | None = None,
    request_timeout_ms: int | None = None,
    *,
    name: str | None = None,
    **kwargs: Any,
) -> None:
    from pathway_tpu.io._format import formatter_for

    policy = retry_policy or RetryPolicy.default()
    timeout = (request_timeout_ms or connect_timeout_ms or 10_000) / 1000.0
    cols = table.column_names()
    fmt = formatter_for("json", cols)

    def post(payload: bytes) -> None:
        delay = policy.first_delay_ms / 1000.0
        for attempt in range(n_retries + 1):
            try:
                req = urllib.request.Request(
                    endpoint,
                    data=payload,
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                urllib.request.urlopen(req, timeout=timeout).read()
                return
            except Exception:
                if attempt == n_retries:
                    raise
                _time.sleep(delay)
                delay *= policy.backoff_factor

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            payload = fmt.format(int(key), row, batch.time, diff)
            post(payload if isinstance(payload, bytes) else payload.encode())

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=name or f"logstash_write:{endpoint}",
    )._register_as_output()
