"""Google Pub/Sub writer (reference ``python/pathway/io/pubsub``).

The reference's own API takes a configured ``pubsub_v1.PublisherClient``
object — client injection is the design, so the connector runs against any
publisher-shaped object (``topic_path`` + ``publish`` returning a future-like
with ``result()``); CI drives it with a fake (``tests/test_gated_connectors``).
``table`` must have exactly one binary column; each change publishes its
payload with ``pathway_time``/``pathway_diff`` attributes."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table


def write(
    table: Table,
    publisher: Any,
    project_id: str,
    topic_id: str,
    *,
    name: str | None = None,
    **kwargs: Any,
) -> None:
    cols = table.column_names()
    if len(cols) != 1:
        raise ValueError(
            "pw.io.pubsub.write expects a table with exactly one (binary) column"
        )
    if hasattr(publisher, "topic_path"):
        topic_path = publisher.topic_path(project_id, topic_id)
    else:
        topic_path = f"projects/{project_id}/topics/{topic_id}"

    def on_batch(batch, columns) -> None:
        futures = []
        for _key, diff, row in batch.rows():
            data = row[0]
            if isinstance(data, str):
                data = data.encode()
            elif not isinstance(data, (bytes, bytearray, memoryview)):
                raise TypeError(
                    "pw.io.pubsub.write requires a binary (bytes/str) column; "
                    f"got {type(data).__name__}"
                )
            futures.append(
                publisher.publish(
                    topic_path,
                    data=bytes(data),
                    pathway_time=str(batch.time),
                    pathway_diff=str(diff),
                )
            )
        for f in futures:  # surface publish errors in the connector channel
            if hasattr(f, "result"):
                f.result()

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=name or f"pubsub_write:{topic_id}",
    )._register_as_output()
