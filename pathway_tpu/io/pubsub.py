"""Gated connector: reference `python/pathway/io/pubsub`. See _gated.py."""

from pathway_tpu.io._gated import gate

write = gate("pubsub", "google-cloud-pubsub")
