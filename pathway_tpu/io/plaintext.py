"""Plaintext connector (reference: ``python/pathway/io/plaintext``)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import fs


def read(path: str, *, mode: str = "streaming", **kwargs: Any):
    return fs.read(path, format="plaintext", mode=mode, **kwargs)
