"""S3 / object-store reader (reference: ``scanner/s3.rs`` + ``python/pathway/io/s3``).

Dependency gate: object-store access needs boto3 (absent in this image) and
network egress. The API surface matches the reference; calls raise until a client
library is available."""

from __future__ import annotations

from typing import Any


class AwsS3Settings:
    def __init__(
        self,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style


def _gate() -> None:
    try:
        import boto3  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "pw.io.s3 requires boto3 and object-store access, which are not "
            "available in this environment"
        ) from None


def read(
    path: str,
    aws_s3_settings: AwsS3Settings | None = None,
    *,
    format: str = "json",  # noqa: A002
    schema: Any = None,
    mode: str = "streaming",
    **kwargs: Any,
):
    _gate()


def read_from_azure(*args: Any, **kwargs: Any):
    _gate()
