"""S3 / object-store connector (reference: ``scanner/s3.rs`` +
``python/pathway/io/s3``).

Real read/write logic over a boto3-style client: bucket listing with
pagination, per-object etag change tracking (the reference's metadata
trackers), Parser-layer decoding, and a block writer producing one object per
output batch. The client is resolved from ``boto3`` when importable; since
this image has neither boto3 nor egress, ``AwsS3Settings(client=...)`` (or
``read(..., client=...)``) injects any object with the same surface —
``tests/test_gated_connectors.py`` drives every path against a dict-backed
fake, so the connector logic is exercised in CI even where the real client
cannot be."""

from __future__ import annotations

import json as _json
import time as _time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, table_from_static_data


class AwsS3Settings:
    def __init__(
        self,
        bucket_name: str | None = None,
        access_key: str | None = None,
        secret_access_key: str | None = None,
        region: str | None = None,
        endpoint: str | None = None,
        with_path_style: bool = False,
        *,
        client: Any = None,
    ):
        self.bucket_name = bucket_name
        self.access_key = access_key
        self.secret_access_key = secret_access_key
        self.region = region
        self.endpoint = endpoint
        self.with_path_style = with_path_style
        #: dependency-injection hook: any boto3-client-shaped object
        self.client = client


def _make_client(settings: AwsS3Settings | None, client: Any = None) -> Any:
    if client is not None:
        return client
    if settings is not None and settings.client is not None:
        return settings.client
    try:
        import boto3
    except ImportError:
        raise NotImplementedError(
            "pw.io.s3 requires boto3 (or an injected client=), neither of "
            "which is available in this environment"
        ) from None
    kwargs: dict[str, Any] = {}
    if settings is not None:
        if settings.region:
            kwargs["region_name"] = settings.region
        if settings.endpoint:
            kwargs["endpoint_url"] = settings.endpoint
        if settings.access_key:
            kwargs["aws_access_key_id"] = settings.access_key
        if settings.secret_access_key:
            kwargs["aws_secret_access_key"] = settings.secret_access_key
        if settings.with_path_style:
            from botocore.config import Config as _BotoConfig

            kwargs["config"] = _BotoConfig(s3={"addressing_style": "path"})
    return boto3.client("s3", **kwargs)


def _split_path(path: str, settings: AwsS3Settings | None) -> tuple[str, str]:
    if path.startswith("s3://"):
        rest = path[len("s3://") :]
        bucket, _, prefix = rest.partition("/")
        return bucket, prefix
    if settings is None or not settings.bucket_name:
        raise ValueError("provide an s3://bucket/prefix path or bucket_name settings")
    return settings.bucket_name, path.lstrip("/")


def _list_objects(client, bucket: str, prefix: str) -> list[tuple[str, str]]:
    """All (key, etag) pairs under ``prefix``, following continuation tokens."""
    out: list[tuple[str, str]] = []
    token: str | None = None
    while True:
        kw: dict[str, Any] = {"Bucket": bucket, "Prefix": prefix}
        if token:
            kw["ContinuationToken"] = token
        resp = client.list_objects_v2(**kw)
        for obj in resp.get("Contents", []):
            out.append((obj["Key"], obj.get("ETag", "")))
        if not resp.get("IsTruncated"):
            break
        token = resp.get("NextContinuationToken")
    return sorted(out)


def _fetch_object(client, bucket: str, key: str) -> bytes | None:
    """Object bytes, or None when the object vanished between listing and
    fetch (the one failure that is an expected race, not an error)."""
    try:
        return client.get_object(Bucket=bucket, Key=key)["Body"].read()
    except Exception:
        return None


def _object_rows(
    client, bucket: str, key: str, fmt: str, schema: schema_mod.SchemaMetaclass
) -> list[tuple]:
    from pathway_tpu.io._format import rows_from_bytes

    body = client.get_object(Bucket=bucket, Key=key)["Body"].read()
    return rows_from_bytes(body, fmt, schema)


def read(
    path: str,
    aws_s3_settings: AwsS3Settings | None = None,
    *,
    format: str = "json",  # noqa: A002
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    client: Any = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    """Read objects under an S3 prefix. ``static`` parses the current listing
    once; ``streaming`` polls the listing and ingests new/changed objects
    (etag-tracked) from a connector thread."""
    if schema is None:
        if format in ("plaintext", "plaintext_by_object"):
            schema = schema_mod.schema_from_types(data=str)
        elif format == "binary":
            schema = schema_mod.schema_from_types(data=bytes)
        else:
            raise ValueError("schema required for csv/json formats")
    cli = _make_client(aws_s3_settings, client)
    bucket, prefix = _split_path(path, aws_s3_settings)

    if mode == "static":
        from pathway_tpu.io.fs import _keys_for

        all_rows: list[tuple] = []
        for key, _etag in _list_objects(cli, bucket, prefix):
            all_rows.extend(_object_rows(cli, bucket, key, format, schema))
        keys = _keys_for(all_rows, schema, salt=hash(path) & 0xFFFF)
        return table_from_static_data(keys, all_rows, schema)

    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    fmt = format

    class _S3Subject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._seen: dict[str, str] = {}
            # object key -> [(row_key, values)] currently live downstream, so
            # overwrites and deletions retract the previous version's rows
            # (the reference's metadata trackers, scanner/s3.rs)
            self._emitted: dict[str, list] = {}
            self._stop = False
            self._bounded = kwargs.get("_bounded", False)

        def _retract(self, obj_key: str) -> None:
            old = self._emitted.pop(obj_key, None)
            if old:
                assert self._node is not None
                self._node.push_many((k, v, -1) for k, v in old)

        def run(self) -> None:
            while not self._stop:
                found = False
                listing = _list_objects(cli, bucket, prefix)
                live = {key for key, _ in listing}
                for gone in [k for k in self._seen if k not in live]:
                    found = True
                    del self._seen[gone]
                    self._retract(gone)
                for key, etag in listing:
                    if self._seen.get(key) == etag:
                        continue
                    changed = key in self._seen
                    self._seen[key] = etag
                    found = True
                    if changed:  # full-object replacement: out with the old
                        self._retract(key)
                    body = _fetch_object(cli, bucket, key)
                    if body is None:
                        # deleted between listing and fetch: the next poll's
                        # listing will treat it as gone and retract
                        self._seen.pop(key, None)
                        continue
                    # parse errors are real errors: they surface through the
                    # connector error channel, not silent re-poll loops
                    from pathway_tpu.io._format import rows_from_bytes

                    values = rows_from_bytes(body, fmt, schema)
                    row_keys_ = self._keys_for(values)
                    assert self._node is not None
                    pairs = [(int(k), v) for k, v in zip(row_keys_, values)]
                    self._node.push_many((k, v, 1) for k, v in pairs)
                    self._emitted[key] = pairs
                if self._bounded and not found:
                    return
                _time.sleep(0.1)

        def on_stop(self) -> None:
            self._stop = True

    return py_read(_S3Subject(), schema=schema, name=name or f"s3:{bucket}/{prefix}")


def write(
    table: Table,
    path: str,
    aws_s3_settings: AwsS3Settings | None = None,
    *,
    format: str = "json",  # noqa: A002
    client: Any = None,
    **kwargs: Any,
) -> None:
    """Write output diffs as one object per batch under ``path`` (the
    data-lake block-writer shape, ``data_lake/writer.rs``): jsonlines records
    carrying ``time``/``diff`` columns."""
    if format not in ("json", "jsonlines"):
        raise ValueError("s3 write supports jsonlines output")
    cli = _make_client(aws_s3_settings, client)
    bucket, prefix = _split_path(path, aws_s3_settings)
    cols = table.column_names()
    counter = {"n": None}

    def on_batch(batch, columns) -> None:
        lines = []
        for _key, diff, row in batch.rows():
            rec = dict(zip(cols, row))
            rec["time"] = batch.time
            rec["diff"] = diff
            lines.append(_json.dumps(rec, default=str))
        if not lines:
            return
        if counter["n"] is None:
            # resume past existing blocks: a restarted run must append, not
            # clobber the previous run's output objects
            existing = [
                k
                for k, _ in _list_objects(cli, bucket, prefix.rstrip("/") + "/block_")
            ]
            indices = []
            for k in existing:
                stem = k.rsplit("block_", 1)[-1].split(".")[0]
                if stem.isdigit():
                    indices.append(int(stem))
            counter["n"] = (max(indices) + 1) if indices else 0
        key = f"{prefix.rstrip('/')}/block_{counter['n']:08d}.jsonl"
        counter["n"] += 1
        cli.put_object(Bucket=bucket, Key=key, Body=("\n".join(lines) + "\n").encode())

    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=f"s3_write:{bucket}/{prefix}",
    )._register_as_output()


def read_from_azure(*args: Any, **kwargs: Any):
    raise NotImplementedError(
        "pw.io.s3.read_from_azure requires the Azure SDK, which is not "
        "available in this environment"
    )
