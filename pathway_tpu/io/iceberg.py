"""Gated connector: reference `python/pathway/io/iceberg`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("iceberg", "the pyiceberg library")
write = gate("iceberg", "the pyiceberg library")
