"""Apache Iceberg connector, implemented against the open table format.

Reference: ``python/pathway/io/iceberg`` over the Rust ``IcebergBatchWriter``
(``/root/reference/src/connectors/data_lake/iceberg.rs:208``, iceberg-rust).
That stack talks to a REST catalog; neither it nor pyiceberg ships on this
image, so — like the Delta connector (``io/deltalake.py``) — this module
implements the protocol itself over a filesystem/object-store warehouse
(the Hadoop-catalog layout):

```
<warehouse>/<namespace...>/<table>/
  metadata/version-hint.text         latest metadata version (int)
  metadata/v<N>.metadata.json        table metadata: schema, snapshots
  metadata/snap-<id>.avro            manifest list  (Avro, io/_avro.py)
  metadata/manifest-<n>.avro         manifest: data-file entries
  data/part-*.parquet                row data (pyarrow), with time/diff
```

Each output batch commits one snapshot: parquet data file → manifest →
manifest list (all manifests so far) → next ``v<N>.metadata.json`` →
``version-hint.text`` swing. The reader replays the current snapshot's data
files (static) or polls ``version-hint.text`` and emits rows of data files it
has not yet processed (streaming), netting recorded ``diff``s like the Delta
reader. ``catalog_uri`` accepts a filesystem path (or ``file://`` URI) as the
warehouse root; a remote REST catalog is a dependency gate, not supported on
this image.
"""

from __future__ import annotations

import json as _json
import os
import time as _time
import uuid
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, table_from_static_data
from pathway_tpu.io import _avro
from pathway_tpu.io.deltalake import _make_coercer, _stringify

_MANIFEST_SCHEMA = {
    "type": "record",
    "name": "manifest_entry",
    "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {
            "name": "data_file",
            "type": {
                "type": "record",
                "name": "r2",
                "fields": [
                    {"name": "file_path", "type": "string"},
                    {"name": "file_format", "type": "string"},
                    {"name": "record_count", "type": "long"},
                    {"name": "file_size_in_bytes", "type": "long"},
                ],
            },
        },
    ],
}

_MANIFEST_LIST_SCHEMA = {
    "type": "record",
    "name": "manifest_file",
    "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "added_snapshot_id", "type": ["null", "long"]},
    ],
}


def _iceberg_type(d) -> str:
    # same physical mapping as the Delta connector (shared coercion helpers
    # require the two connectors' string-degradation sets to stay identical)
    from pathway_tpu.io.deltalake import _delta_type

    return {"long": "long", "double": "double", "boolean": "boolean", "binary": "binary"}.get(
        _delta_type(d), "string"
    )


def _table_root(
    catalog_uri: str, namespace: list[str], table_name: str, warehouse: str | None
) -> str:
    root = warehouse or catalog_uri
    if root.startswith("file://"):
        root = root[len("file://"):]
    if root.startswith(("http://", "https://")):
        raise NotImplementedError(
            "pw.io.iceberg: REST catalogs need a catalog client not available "
            "in this environment; pass a filesystem warehouse path instead"
        )
    return os.path.join(root, *namespace, table_name)


def _meta_dir(troot: str) -> str:
    return os.path.join(troot, "metadata")


def _current_version(troot: str) -> int:
    hint = os.path.join(_meta_dir(troot), "version-hint.text")
    try:
        with open(hint) as fh:
            return int(fh.read().strip())
    except (FileNotFoundError, ValueError):
        return 0


def _max_version_on_disk(troot: str) -> int:
    """Highest v<N>.metadata.json present — a writer may have died between
    creating vN and swinging the hint, so the hint alone can lag the disk."""
    try:
        names = os.listdir(_meta_dir(troot))
    except FileNotFoundError:
        return 0
    best = 0
    for fn in names:
        if fn.startswith("v") and fn.endswith(".metadata.json"):
            stem = fn[1 : -len(".metadata.json")]
            if stem.isdigit():
                best = max(best, int(stem))
    return best


def _load_metadata(troot: str, version: int) -> dict | None:
    p = os.path.join(_meta_dir(troot), f"v{version}.metadata.json")
    try:
        with open(p) as fh:
            return _json.load(fh)
    except FileNotFoundError:
        return None
    except ValueError:
        # truncated metadata (a pre-r6 writer died mid json.dump — r6 writers
        # publish atomically via link): treat as absent so the commit loop's
        # hint fallback engages instead of crashing every later commit
        return None


def _snapshot_files(troot: str, meta: dict) -> list[str]:
    """Data-file paths of the metadata's current snapshot."""
    snap_id = meta.get("current-snapshot-id")
    snap = next(
        (s for s in meta.get("snapshots", []) if s["snapshot-id"] == snap_id), None
    )
    if snap is None:
        return []
    _schema, manifests = _avro.read_container(
        os.path.join(troot, snap["manifest-list"])
    )
    files: list[str] = []
    seen: set[str] = set()
    for m in manifests:
        _s, entries = _avro.read_container(os.path.join(troot, m["manifest_path"]))
        for e in entries:
            fp = e["data_file"]["file_path"]
            if e["status"] != 2 and fp not in seen:  # 2 = DELETED
                seen.add(fp)
                files.append(fp)
    return files


def write(
    table: Table,
    catalog_uri: str,
    namespace: list[str],
    table_name: str,
    *,
    warehouse: str | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode

    troot = _table_root(catalog_uri, namespace, table_name, warehouse)
    cols = table.column_names()
    if "time" in cols or "diff" in cols:
        raise ValueError(
            "pw.io.iceberg.write adds its own time/diff columns; rename the "
            "table's 'time'/'diff' columns before writing"
        )
    dtypes = dict(table._schema.dtypes())
    os.makedirs(_meta_dir(troot), exist_ok=True)
    os.makedirs(os.path.join(troot, "data"), exist_ok=True)
    stringly = {c for c in cols if _iceberg_type(dtypes.get(c, dt.STR)) == "string"}
    table_uuid = str(uuid.uuid4())

    def commit(data_path: str, n_rows: int) -> None:
        # optimistic concurrency on the metadata version: vN must be CREATED,
        # never overwritten; the base version scans the DISK, not just the
        # hint — a writer that died after creating vN but before the hint
        # swing must not trap every later commit in a FileExistsError spin
        while True:
            version = max(_current_version(troot), _max_version_on_disk(troot))
            # prev must be the SAME version the commit builds upon: after a
            # FileExistsError retry (or a hint lagging the disk), loading prev
            # from the stale hint would build a manifest list that silently
            # omits the winning writer's data files — a lost update
            prev = _load_metadata(troot, version)
            if prev is None and version != _current_version(troot):
                prev = _load_metadata(troot, _current_version(troot))
            new_version = version + 1
            snap_id = int(_time.time_ns() % (2**62))
            mdir = _meta_dir(troot)
            manifest_name = f"manifest-{new_version:08d}-{uuid.uuid4().hex[:8]}.avro"
            _avro.write_container(
                os.path.join(mdir, manifest_name),
                _MANIFEST_SCHEMA,
                [
                    {
                        "status": 1,  # ADDED
                        "snapshot_id": snap_id,
                        "data_file": {
                            "file_path": data_path,
                            "file_format": "PARQUET",
                            "record_count": n_rows,
                            "file_size_in_bytes": os.path.getsize(
                                os.path.join(troot, data_path)
                            ),
                        },
                    }
                ],
            )
            # manifest list = every manifest so far (full table state)
            prev_manifests: list[dict] = []
            if prev is not None and prev.get("current-snapshot-id") is not None:
                snap = next(
                    (
                        s
                        for s in prev.get("snapshots", [])
                        if s["snapshot-id"] == prev["current-snapshot-id"]
                    ),
                    None,
                )
                if snap is not None:
                    _s, prev_manifests = _avro.read_container(
                        os.path.join(troot, snap["manifest-list"])
                    )
            mlist_name = f"snap-{snap_id}-{uuid.uuid4().hex[:8]}.avro"
            mpath = os.path.join("metadata", manifest_name)
            _avro.write_container(
                os.path.join(mdir, mlist_name),
                _MANIFEST_LIST_SCHEMA,
                prev_manifests
                + [
                    {
                        "manifest_path": mpath,
                        "manifest_length": os.path.getsize(
                            os.path.join(mdir, manifest_name)
                        ),
                        "partition_spec_id": 0,
                        "added_snapshot_id": snap_id,
                    }
                ],
            )
            fields = [
                {
                    "id": i + 1,
                    "name": c,
                    "required": False,
                    "type": _iceberg_type(dtypes.get(c, dt.STR)),
                }
                for i, c in enumerate(cols)
            ]
            fields += [
                {"id": len(cols) + 1, "name": "time", "required": False, "type": "long"},
                {"id": len(cols) + 2, "name": "diff", "required": False, "type": "long"},
            ]
            snapshots = (prev.get("snapshots", []) if prev else []) + [
                {
                    "snapshot-id": snap_id,
                    "sequence-number": new_version,
                    "timestamp-ms": int(_time.time() * 1000),
                    "manifest-list": os.path.join("metadata", mlist_name),
                    "summary": {"operation": "append"},
                }
            ]
            meta = {
                "format-version": 2,
                "table-uuid": (prev or {}).get("table-uuid", table_uuid),
                "location": troot,
                "last-sequence-number": new_version,
                "schemas": [{"schema-id": 0, "type": "struct", "fields": fields}],
                "current-schema-id": 0,
                "snapshots": snapshots,
                "current-snapshot-id": snap_id,
            }
            # publish vN atomically: the file must appear fully written (a
            # concurrent writer's max-version scan may json.load it the
            # instant it exists) AND exclusively (exactly one writer may own
            # vN). Write to a private tmp, then hard-link into place — link
            # fails with FileExistsError when another writer won the version.
            vpath = os.path.join(mdir, f"v{new_version}.metadata.json")
            tmp_meta = os.path.join(
                mdir, f"v{new_version}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
            )
            with open(tmp_meta, "w") as fh:
                _json.dump(meta, fh)
            try:
                os.link(tmp_meta, vpath)
            except FileExistsError:
                os.unlink(tmp_meta)
                continue  # another writer won the version: retry on top of it
            os.unlink(tmp_meta)
            tmp = os.path.join(mdir, f"version-hint.tmp{os.getpid()}")
            with open(tmp, "w") as fh:
                fh.write(str(new_version))
            os.replace(tmp, os.path.join(mdir, "version-hint.text"))
            return

    def on_batch(batch, columns) -> None:
        from pathway_tpu.engine.blocks import column_to_list

        n = len(batch)
        if not n:
            return
        arrays: dict[str, list] = {}
        for c in cols:
            vals = column_to_list(batch.data[c])
            if c in stringly:
                vals = [_stringify(v) for v in vals]
            arrays[c] = vals
        arrays["time"] = [batch.time] * n
        arrays["diff"] = batch.diffs.tolist()
        part = os.path.join("data", f"part-{uuid.uuid4().hex}.parquet")
        pq.write_table(pa.table(arrays), os.path.join(troot, part))
        commit(part, n)

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=name or f"iceberg_write:{table_name}",
    )._register_as_output()


def _file_rows(troot: str, fpath: str, schema_cols: list[str], dtypes: dict) -> list[tuple]:
    import pyarrow.parquet as pq

    coercers = {
        c: _make_coercer(dtypes[c])
        for c in schema_cols
        if c in dtypes and dt.unoptionalize(dtypes[c]) not in (dt.STR, dt.ANY)
    }
    t = pq.read_table(os.path.join(troot, fpath))
    data = {c: t.column(c).to_pylist() for c in t.column_names}
    n = t.num_rows
    diffs = data.get("diff") or [1] * n
    col_lists = [data.get(c) or [None] * n for c in schema_cols]
    for i, c in enumerate(schema_cols):
        conv = coercers.get(c)
        if conv is not None and data.get(c) is not None:
            col_lists[i] = [None if v is None else conv(v) for v in col_lists[i]]
    return list(zip(zip(*col_lists) if col_lists else [()] * n, map(int, diffs)))


def read(
    catalog_uri: str,
    namespace: list[str],
    table_name: str,
    *,
    schema: schema_mod.SchemaMetaclass,
    mode: str = "streaming",
    warehouse: str | None = None,
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    troot = _table_root(catalog_uri, namespace, table_name, warehouse)
    cols = schema.column_names()
    if mode not in ("static", "streaming"):
        raise ValueError(f"unknown iceberg read mode {mode!r}")
    dtypes = schema.dtypes()

    if mode == "static":
        from pathway_tpu.io.fs import _keys_for

        meta = _load_metadata(troot, _current_version(troot))
        net: dict[tuple, int] = {}
        order: list[tuple] = []
        if meta is not None:
            for fp in _snapshot_files(troot, meta):
                for r, d in _file_rows(troot, fp, cols, dtypes):
                    if r not in net:
                        order.append(r)
                    net[r] = net.get(r, 0) + d
        all_rows = [r for r in order for _ in range(max(net[r], 0))]
        keys = _keys_for(all_rows, schema, salt=hash(troot) & 0xFFFF)
        return table_from_static_data(keys, all_rows, schema)

    from pathway_tpu.internals.keys import stable_hash_obj
    from pathway_tpu.io.fs import _keys_for
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    pks = schema.primary_key_columns()

    class _IcebergSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._seen_version = 0
            self._seen_files: set[str] = set()
            self._stop = False
            self._bounded = kwargs.get("_bounded", False)

        def run(self) -> None:
            while not self._stop:
                version = _current_version(troot)
                found = False
                if version > self._seen_version:
                    meta = _load_metadata(troot, version)
                    if meta is not None:
                        for fp in _snapshot_files(troot, meta):
                            if fp in self._seen_files:
                                continue
                            found = True
                            vrows = _file_rows(troot, fp, cols, dtypes)
                            values_list = [r for r, _d in vrows]
                            if pks:
                                # primary-key keys: streaming and static modes
                                # agree; updates net as retract+insert in place
                                keys_ = _keys_for(values_list, schema, salt=0)
                            else:
                                # content-derived: a replayed retraction must
                                # net against its insert
                                keys_ = [int(stable_hash_obj(r)) for r in values_list]
                            assert self._node is not None
                            self._node.push_many(
                                (int(k), r, d) for k, (r, d) in zip(keys_, vrows)
                            )
                            self._seen_files.add(fp)
                        self._seen_version = version
                if self._bounded and not found:
                    return
                _time.sleep(0.1)

        # persistence contract: committed version + processed files
        def offset_state(self) -> dict:
            return {
                "version": self._seen_version,
                "files": sorted(self._seen_files),
                "seq": self._seq,
            }

        def seek(self, state: dict) -> None:
            self._seen_version = int(state.get("version", 0))
            self._seen_files = set(state.get("files", []))
            self._seq = int(state.get("seq", 0))

        def on_stop(self) -> None:
            self._stop = True

    return py_read(
        _IcebergSubject(),
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"iceberg:{table_name}",
    )
