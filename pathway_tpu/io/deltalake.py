"""Gated connector: reference `python/pathway/io/deltalake`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("deltalake", "the deltalake library")
write = gate("deltalake", "the deltalake library")
