"""Delta Lake connector (reference ``python/pathway/io/deltalake`` over the
Rust ``DeltaBatchWriter``, ``src/connectors/data_lake/delta.rs:126``).

Implemented directly against the open Delta transaction protocol — a
``_delta_log/`` directory of ordered JSON commits plus parquet data files —
using the image's ``pyarrow`` for parquet. Tables written here carry a
protocol/metaData commit and per-batch ``add`` actions with the standard
Spark ``schemaString``, so delta-rs/Spark readers consume them directly; the
reader replays the commit log (``add``/``remove`` actions) and, in streaming
mode, polls for new versions — each appended version's rows enter the
dataflow with their recorded ``diff``.

Output rows carry the engine's ``time``/``diff`` columns like every other
diff-stream sink (the reference writes the same columns)."""

from __future__ import annotations

import json as _json
import os
import time as _time
import uuid
from typing import Any

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, table_from_static_data

_LOG_DIR = "_delta_log"


def _delta_type(d) -> str:
    d = dt.unoptionalize(d)
    if d == dt.INT:
        return "long"
    if d == dt.FLOAT:
        return "double"
    if d == dt.BOOL:
        return "boolean"
    if d == dt.BYTES:
        return "binary"
    return "string"


def _schema_string(cols: list[str], dtypes: dict) -> str:
    fields = [
        {
            "name": c,
            "type": _delta_type(dtypes.get(c, dt.STR)),
            "nullable": True,
            "metadata": {},
        }
        for c in cols
    ]
    fields += [
        {"name": "time", "type": "long", "nullable": True, "metadata": {}},
        {"name": "diff", "type": "long", "nullable": True, "metadata": {}},
    ]
    return _json.dumps({"type": "struct", "fields": fields})


def _log_path(uri: str, version: int) -> str:
    return os.path.join(uri, _LOG_DIR, f"{version:020d}.json")


def _existing_versions(uri: str) -> list[int]:
    log_dir = os.path.join(uri, _LOG_DIR)
    if not os.path.isdir(log_dir):
        return []
    out = []
    for fn in os.listdir(log_dir):
        stem = fn.split(".")[0]
        if fn.endswith(".json") and stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def write(table: Table, uri: str, *, name: str | None = None, **kwargs: Any) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode

    cols = table.column_names()
    if "time" in cols or "diff" in cols:
        raise ValueError(
            "pw.io.deltalake.write adds its own time/diff columns; rename the "
            "table's 'time'/'diff' columns before writing"
        )
    dtypes = dict(table._schema.dtypes())
    os.makedirs(os.path.join(uri, _LOG_DIR), exist_ok=True)
    state = {"version": (max(_existing_versions(uri), default=-1))}

    def commit(actions: list[dict]) -> None:
        # Delta's optimistic concurrency: the version file must be CREATED,
        # never overwritten — on conflict, re-scan and retry the next version
        while True:
            state["version"] += 1
            version = state["version"]
            acts = actions
            if version == 0:
                acts = [
                    {
                        "protocol": {"minReaderVersion": 1, "minWriterVersion": 2}
                    },
                    {
                        "metaData": {
                            "id": str(uuid.uuid4()),
                            "format": {"provider": "parquet", "options": {}},
                            "schemaString": _schema_string(cols, dtypes),
                            "partitionColumns": [],
                            "configuration": {},
                            "createdTime": int(_time.time() * 1000),
                        }
                    },
                ] + actions
            payload = "\n".join(_json.dumps(a) for a in acts) + "\n"
            # write the FULL content to a temp file, then atomically link it
            # into place — put-if-absent with content, so a concurrent reader
            # can never observe an empty/truncated version file
            tmp = _log_path(uri, version) + f".tmp{os.getpid()}"
            with open(tmp, "w") as fh:
                fh.write(payload)
            try:
                os.link(tmp, _log_path(uri, version))
            except FileExistsError:
                os.unlink(tmp)
                state["version"] = max(_existing_versions(uri), default=-1)
                continue
            os.unlink(tmp)
            return

    # columns whose dtype maps to Delta "string" get stringified at write so
    # the parquet column type always matches the declared schemaString;
    # _stringify writes the canonical form that read()'s _coerce_back parses
    stringly = {
        c
        for c in cols
        if _delta_type(dtypes.get(c, dt.STR)) == "string"
    }

    def on_batch(batch, columns) -> None:
        from pathway_tpu.engine.blocks import column_to_list

        n = len(batch)
        if not n:
            return
        arrays: dict[str, list] = {}
        for c in cols:  # one C-speed pass per column, no per-row loop
            vals = column_to_list(batch.data[c])
            if c in stringly:
                vals = [_stringify(v) for v in vals]
            arrays[c] = vals
        arrays["time"] = [batch.time] * n
        arrays["diff"] = batch.diffs.tolist()
        part = f"part-{state['version'] + 1:05d}-{uuid.uuid4()}.snappy.parquet"
        fpath = os.path.join(uri, part)
        pq.write_table(pa.table(arrays), fpath)
        commit(
            [
                {
                    "add": {
                        "path": part,
                        "partitionValues": {},
                        "size": os.path.getsize(fpath),
                        "modificationTime": int(_time.time() * 1000),
                        "dataChange": True,
                    }
                }
            ]
        )

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [table._node],
        name=name or f"deltalake_write:{uri}",
    )._register_as_output()


def _plain(v):
    """Numpy-free canonical form of a cell for tuple stringification: the
    result's ``repr`` must survive ``ast.literal_eval`` (numpy-2 scalar reprs
    like ``np.int64(1)`` do not)."""
    import numpy as np

    if isinstance(v, np.datetime64):
        return str(v.astype("datetime64[ns]"))
    if isinstance(v, np.timedelta64):
        return int(v.astype("timedelta64[ns]").astype(np.int64))
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, (tuple, list)):
        return tuple(_plain(e) for e in v)
    return v


def _stringify(v) -> str | None:
    """Canonical string form for a Delta 'string'-typed cell, chosen so
    ``_coerce_back`` can recover the original value: durations as integer
    nanoseconds, datetimes as numpy ISO, Json as compact JSON, tuples as
    literal_eval-able reprs of plain-Python elements."""
    import numpy as np

    if v is None:
        return None
    if isinstance(v, np.timedelta64):
        return str(int(v.astype("timedelta64[ns]").astype(np.int64)))
    if isinstance(v, (tuple, list)):
        return repr(_plain(v))
    return str(v)


def _make_coercer(d):
    """One converter per column dtype (not per cell): the inverse of the
    write-side stringification (advisor r4: round-trips must not silently
    yield str where the schema says datetime/duration/tuple/JSON)."""
    import numpy as np

    from pathway_tpu.io._format import coerce_scalar

    d = dt.unoptionalize(d)
    if d in (dt.DATE_TIME_NAIVE, dt.DATE_TIME_UTC):
        def conv(v):
            try:
                return np.datetime64(v)
            except ValueError:
                return v
        return conv
    if d == dt.DURATION:
        def conv(v):
            try:
                return np.timedelta64(int(v), "ns")
            except (ValueError, TypeError):
                return v
        return conv
    if isinstance(d, dt.Tuple):
        import ast

        # element-wise coercers for fixed-arity tuples (ANY_TUPLE has none)
        elem_convs = [_make_coercer(a) for a in d.args] if d.args else None

        def conv(v):
            try:
                parsed = ast.literal_eval(v)
            except (ValueError, SyntaxError):
                return v
            if not isinstance(parsed, (tuple, list)):
                return v
            parsed = tuple(parsed)
            if elem_convs is not None and len(elem_convs) == len(parsed):
                return tuple(c(e) for c, e in zip(elem_convs, parsed))
            return parsed
        return conv
    return lambda v: coerce_scalar(v, d)


def _version_rows(
    uri: str, version: int, schema_cols: list[str], dtypes: dict | None = None
) -> list[tuple]:
    """(values-tuple, diff) rows added by one commit version, coerced back to
    the declared schema dtypes."""
    import pyarrow.parquet as pq

    dtypes = dtypes or {}
    # columns needing value coercion (everything Delta stores as 'string'
    # except true STR columns, plus ints/floats pyarrow may widen)
    coercers = {
        c: _make_coercer(dtypes[c])
        for c in schema_cols
        if c in dtypes and dt.unoptionalize(dtypes[c]) not in (dt.STR, dt.ANY)
    }
    rows: list[tuple] = []
    with open(_log_path(uri, version)) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            action = _json.loads(line)
            if "add" in action:
                t = pq.read_table(os.path.join(uri, action["add"]["path"]))
                data = {c: t.column(c).to_pylist() for c in t.column_names}
                n = t.num_rows
                diffs = data.get("diff") or [1] * n
                col_lists = [data.get(c) or [None] * n for c in schema_cols]
                for c_idx, c in enumerate(schema_cols):
                    conv = coercers.get(c)
                    if conv is not None and data.get(c) is not None:
                        col_lists[c_idx] = [
                            None if v is None else conv(v) for v in col_lists[c_idx]
                        ]
                rows.extend(
                    zip(zip(*col_lists) if col_lists else [()] * n, map(int, diffs))
                )
    return rows


def read(
    uri: str,
    *,
    schema: schema_mod.SchemaMetaclass,
    mode: str = "streaming",
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    cols = schema.column_names()
    if mode not in ("static", "streaming"):
        raise ValueError(f"unknown deltalake read mode {mode!r}")

    if mode == "static":
        from pathway_tpu.io.fs import _keys_for

        net: dict[tuple, int] = {}
        order: list[tuple] = []
        for v in _existing_versions(uri):
            for r, d in _version_rows(uri, v, cols, schema.dtypes()):
                if r not in net:
                    order.append(r)
                net[r] = net.get(r, 0) + d
        all_rows = [r for r in order for _ in range(max(net[r], 0))]
        keys = _keys_for(all_rows, schema, salt=hash(uri) & 0xFFFF)
        return table_from_static_data(keys, all_rows, schema)

    from pathway_tpu.internals.keys import stable_hash_obj
    from pathway_tpu.io.fs import _keys_for
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    pks = schema.primary_key_columns()

    class _DeltaSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._next_version = 0
            self._stop = False
            self._bounded = kwargs.get("_bounded", False)

        def run(self) -> None:
            while not self._stop:
                versions = [
                    v for v in _existing_versions(uri) if v >= self._next_version
                ]
                found = False
                for v in versions:
                    found = True
                    vrows = _version_rows(uri, v, cols, schema.dtypes())
                    if not vrows:
                        self._next_version = v + 1
                        continue
                    values_list = [r for r, _d in vrows]
                    if pks:
                        # primary-key keys: streaming and static modes agree,
                        # and updates surface as in-place retract+insert
                        keys_ = _keys_for(values_list, schema, salt=0)
                    else:
                        # content-derived: a replayed retraction must net
                        # against its insert (sequential keys never match)
                        keys_ = [int(stable_hash_obj(r)) for r in values_list]
                    assert self._node is not None
                    self._node.push_many(
                        (int(k), r, d)
                        for k, (r, d) in zip(keys_, vrows)
                    )
                    self._next_version = v + 1
                if self._bounded and not found:
                    return
                _time.sleep(0.1)

        # persistence contract: the committed version is the offset
        def offset_state(self) -> dict:
            return {"next_version": self._next_version, "seq": self._seq}

        def seek(self, state: dict) -> None:
            self._next_version = int(state.get("next_version", 0))
            self._seq = int(state.get("seq", 0))

        def on_stop(self) -> None:
            self._stop = True

    return py_read(
        _DeltaSubject(),
        schema=schema,
        autocommit_duration_ms=autocommit_duration_ms,
        name=name or f"deltalake:{uri}",
    )
