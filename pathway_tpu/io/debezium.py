"""Debezium CDC connector (reference: ``python/pathway/io/debezium`` over
``DebeziumMessageParser``, ``src/connectors/data_format.rs:1433``).

Consumes Debezium change envelopes from a Kafka topic; ``op`` c/r/u/d become
insert/retract deltas keyed by the schema's primary keys. Envelopes arrive
with or without the Connect schema block, and null-payload log-compaction
tombstones parse into keyed deletes (``tombstones=True``, the default here —
harmless for the default diff-native session, which drops the valueless
event because the ``op: d`` envelope already retracted the row)."""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io import kafka as _kafka
from pathway_tpu.io._format import DebeziumMessageParser


def read(
    broker: Any,
    topic: str,
    *,
    schema: schema_mod.SchemaMetaclass,
    mode: str = "streaming",
    name: str | None = None,
    tombstones: bool = True,
    **kwargs: Any,
) -> Table:
    if not schema.primary_key_columns():
        raise ValueError("debezium streams require a schema with primary keys")
    return _kafka.read(
        broker,
        topic,
        schema=schema,
        parser=DebeziumMessageParser(schema, tombstones=tombstones),
        format="debezium",
        mode=mode,
        name=name or f"debezium:{topic}",
        **kwargs,
    )
