"""Gated connector: reference `python/pathway/io/pyfilesystem`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("pyfilesystem", "the fs (PyFilesystem2) library")
