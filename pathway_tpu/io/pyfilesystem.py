"""PyFilesystem connector (reference ``python/pathway/io/pyfilesystem``).

The reference's API takes an ``fs.base.FS`` OBJECT (``fs.open_fs(...)``) —
the filesystem itself is the injected client, so the connector runs against
any object with the small FS surface it touches (``listdir``/``isdir``/
``readbytes`` or ``open``, ``getinfo`` with a modified timestamp when
available). Static mode reads the tree once; streaming polls for new or
modified files, retracting replaced versions like the fs/s3 connectors."""

from __future__ import annotations

import time as _time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table, table_from_static_data


def _walk(source_fs: Any, path: str) -> list[str]:
    out: list[str] = []
    stack = [path.rstrip("/") or "/"]
    while stack:
        cur = stack.pop()
        for entry in sorted(source_fs.listdir(cur)):
            full = f"{cur.rstrip('/')}/{entry}"
            if source_fs.isdir(full):
                stack.append(full)
            else:
                out.append(full)
    return sorted(out)


def _read_bytes(source_fs: Any, path: str) -> bytes:
    if hasattr(source_fs, "readbytes"):
        return source_fs.readbytes(path)
    with source_fs.open(path, "rb") as fh:  # pragma: no cover - alt surface
        return fh.read()


def _mtime(source_fs: Any, path: str) -> Any:
    try:
        info = source_fs.getinfo(path, namespaces=["details"])
        return getattr(info, "modified", None) or info.raw.get("details", {}).get(
            "modified"
        )
    except Exception:
        return None


def read(
    source_fs: Any,
    path: str = "/",
    *,
    format: str = "binary",  # noqa: A002
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    with_metadata: bool = False,
    name: str | None = None,
    refresh_interval: float = 0.5,
    **kwargs: Any,
) -> Table:
    from pathway_tpu.io._format import rows_from_bytes

    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = schema_mod.schema_from_types(data=str)
        elif format == "binary":
            schema = schema_mod.schema_from_types(data=bytes)
        else:
            raise ValueError("schema required for csv/json formats")
    base_schema = schema  # parse data columns only; _metadata appends after
    if with_metadata:
        schema = schema | schema_mod.schema_from_types(_metadata=dict)

    def file_rows(fpath: str) -> list[tuple]:
        rows = rows_from_bytes(_read_bytes(source_fs, fpath), format, base_schema)
        if with_metadata:
            from pathway_tpu.internals.json import Json

            meta = Json({"path": fpath, "modified_at": str(_mtime(source_fs, fpath))})
            rows = [r + (meta,) for r in rows]
        return rows

    if mode == "static":
        from pathway_tpu.io.fs import _keys_for

        all_rows: list[tuple] = []
        for fpath in _walk(source_fs, path):
            all_rows.extend(file_rows(fpath))
        keys = _keys_for(all_rows, schema, salt=hash(path) & 0xFFFF)
        return table_from_static_data(keys, all_rows, schema)

    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    class _FsSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._seen: dict[str, Any] = {}
            self._emitted: dict[str, list] = {}
            self._stop = False
            self._bounded = kwargs.get("_bounded", False)

        def _retract(self, fpath: str) -> None:
            old = self._emitted.pop(fpath, None)
            if old:
                assert self._node is not None
                self._node.push_many((k, v, -1) for k, v in old)

        def run(self) -> None:
            while not self._stop:
                found = False
                try:
                    listing = _walk(source_fs, path)
                except Exception:
                    # transient FS error / directory raced away mid-walk:
                    # retry next poll instead of failing the pipeline
                    _time.sleep(refresh_interval)
                    continue
                live = set(listing)
                for gone in [f for f in self._seen if f not in live]:
                    found = True
                    del self._seen[gone]
                    self._retract(gone)
                for fpath in listing:
                    stamp = _mtime(source_fs, fpath)
                    if fpath in self._seen and self._seen[fpath] == stamp:
                        continue
                    changed = fpath in self._seen
                    self._seen[fpath] = stamp
                    found = True
                    if changed:
                        self._retract(fpath)
                    try:
                        values = file_rows(fpath)
                    except Exception:
                        self._seen.pop(fpath, None)  # vanished mid-read
                        continue
                    row_keys_ = self._keys_for(values)
                    assert self._node is not None
                    pairs = [(int(k), v) for k, v in zip(row_keys_, values)]
                    self._node.push_many((k, v, 1) for k, v in pairs)
                    self._emitted[fpath] = pairs
                if self._bounded and not found:
                    return
                _time.sleep(refresh_interval)

        def on_stop(self) -> None:
            self._stop = True

    return py_read(_FsSubject(), schema=schema, name=name or f"pyfilesystem:{path}")
