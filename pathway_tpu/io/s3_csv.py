"""Gated connector: reference `python/pathway/io/s3_csv`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("s3_csv", "boto3 and object-store access")
