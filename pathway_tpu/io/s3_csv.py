"""CSV-over-S3 (reference ``python/pathway/io/s3_csv``): ``pw.io.s3.read``
with ``format="csv"`` pre-bound."""

from __future__ import annotations

from typing import Any

from pathway_tpu.io import s3 as _s3


def read(path: str, aws_s3_settings: Any = None, **kwargs: Any):
    kwargs.setdefault("format", "csv")
    return _s3.read(path, aws_s3_settings, **kwargs)
