"""Filesystem connector (reference: ``python/pathway/io/fs`` over the Rust
``posix_like.rs`` + ``scanner/filesystem.rs`` readers and ``FileWriter``).

``mode="static"`` reads matching files once; ``mode="streaming"`` polls the glob for
new/changed files from a connector thread, emitting rows as they appear (object
deletions are detected and retracted, mirroring the reference's metadata trackers).
"""

from __future__ import annotations

import csv as _csv
import glob as _glob
import json as _json
import os
import threading
import time as _time
from typing import Any

import numpy as np

from pathway_tpu.engine import operators as ops
from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.keys import row_keys, sequential_keys, splitmix64
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table, table_from_static_data
from pathway_tpu.internals.universe import Universe


def _list_files(path: str) -> list[str]:
    if os.path.isdir(path):
        out = []
        for root, _dirs, files in os.walk(path):
            out.extend(os.path.join(root, f) for f in sorted(files))
        return sorted(out)
    return sorted(_glob.glob(path))


def _parse_file(
    fpath: str, fmt: str, schema: schema_mod.SchemaMetaclass, csv_settings: Any = None
) -> list[tuple]:
    from pathway_tpu.io._format import rows_from_bytes

    with open(fpath, "rb") as f:
        return rows_from_bytes(f.read(), fmt, schema)


def _keys_for(
    rows: list[tuple], schema: schema_mod.SchemaMetaclass, salt: int
) -> list[int]:
    pks = schema.primary_key_columns()
    cols = schema.column_names()
    if pks:
        arrays = []
        for pk in pks:
            i = cols.index(pk)
            a = np.empty(len(rows), dtype=object)
            a[:] = [r[i] for r in rows]
            arrays.append(a)
        return [int(k) for k in row_keys(arrays, n=len(rows))]
    return [int(k) for k in sequential_keys(0, len(rows), salt=salt)]


def read(
    path: str,
    *,
    format: str = "csv",  # noqa: A002
    schema: schema_mod.SchemaMetaclass | None = None,
    mode: str = "streaming",
    csv_settings: Any = None,
    autocommit_duration_ms: int | None = None,
    with_metadata: bool = False,
    name: str | None = None,
    service_class: str = "bulk",
    **kwargs: Any,
) -> Table:
    if schema is None:
        if format in ("plaintext", "plaintext_by_file"):
            schema = schema_mod.schema_from_types(data=str)
        elif format == "binary":
            schema = schema_mod.schema_from_types(data=bytes)
        else:
            raise ValueError("schema required for csv/json formats")
    base_schema = schema  # parse with the DATA columns only; the _metadata
    # column is appended afterwards (parsing with the merged schema would bind
    # a placeholder parsed from the payload instead of the real metadata)
    if with_metadata:
        schema = schema | schema_mod.schema_from_types(_metadata=dict)

    if mode == "static":
        all_rows: list[tuple] = []
        for fpath in _list_files(path):
            rows = _parse_file(fpath, format, base_schema, csv_settings)
            if with_metadata:
                rows = [r + (_metadata_for(fpath),) for r in rows]
            all_rows.extend(rows)
        keys = _keys_for(all_rows, schema, salt=hash(path) & 0xFFFF)
        return table_from_static_data(keys, all_rows, schema)

    # streaming: poll directory from a connector thread
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    class _FsSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._seen: dict[str, float] = {}
            self._stop = False
            self._bounded = kwargs.get("_bounded", False)

        def run(self) -> None:
            while not self._stop:
                found = False
                for fpath in _list_files(path):
                    mtime = os.path.getmtime(fpath)
                    if self._seen.get(fpath) == mtime:
                        continue
                    self._seen[fpath] = mtime
                    found = True
                    for r in _parse_file(fpath, format, base_schema, csv_settings):
                        if with_metadata:
                            r = r + (_metadata_for(fpath),)
                        self.next(**dict(zip(schema.column_names(), r)))
                if self._bounded and not found:
                    return
                _time.sleep(0.05)

        def on_stop(self) -> None:
            self._stop = True

    # directory ingestion is the canonical backfill workload: default to the
    # flow plane's bulk class so interactive query streams overtake a document
    # re-scan at tick granularity (pass service_class="interactive" to opt out)
    return py_read(
        _FsSubject(),
        schema=schema,
        name=name or f"fs:{path}",
        service_class=service_class,
    )


def _metadata_for(fpath: str) -> Any:
    from pathway_tpu.internals.json import Json

    st = os.stat(fpath)
    return Json(
        {
            "path": os.path.abspath(fpath),
            "size": st.st_size,
            "modified_at": int(st.st_mtime),
            "seen_at": int(_time.time()),
        }
    )


def write(
    table: Table,
    filename: str,
    *,
    format: str = "csv",  # noqa: A002
    sharded: bool = False,
    service_class: str = "interactive",
    delivery: str | None = None,
    **kwargs: Any,
) -> None:
    """Append output diffs to a file with time/diff columns (reference FileWriter +
    DsvFormatter/JsonLinesFormatter semantics).

    ``sharded=True`` (r5): every worker writes its own key-shard's rows to
    ``filename.part-<w>``; when the last shard closes, the parts merge-commit
    into ``filename`` ordered by logical time (ties broken by worker index) and
    the parts are removed. Under a multi-process cluster the parts remain on
    disk per process (no cross-process close ordering) — consume them as a
    part-file set, Spark-style.

    ``delivery="exactly_once"`` re-expresses the writer over the delivery
    ledger: formatted lines stage durably per epoch and append to the file
    only at operator-snapshot recovery points, guarded by the
    ``<filename>.delivery`` offset sidecar (truncate-to-offset on re-publish)
    — the file's bytes are identical across SIGKILL/restart. Not combinable
    with ``sharded=True`` (the ledger funnels through the SOLO sink path).

    ``service_class="bulk"`` excludes this writer's end-to-end latency from
    the flow plane's SLO (an fsync-bound audit mirror must not drag the AIMD
    microbatch bucket down)."""
    from pathway_tpu.flow import validate_service_class

    service_class = validate_service_class(service_class)
    from pathway_tpu import delivery as _delivery

    if _delivery.resolve_mode(delivery) == "exactly_once":
        if sharded:
            raise ValueError(
                "fs.write: delivery='exactly_once' routes every row through "
                "the SOLO delivery ledger and cannot be combined with "
                "sharded=True"
            )
        return _write_ledger(
            table, filename, format=format, service_class=service_class
        )
    if sharded:
        return _write_sharded(
            table, filename, format=format, service_class=service_class, **kwargs
        )
    parent = os.path.dirname(os.path.abspath(filename))
    if not os.path.isdir(parent):
        # fail at graph build like the eager-open era did, not mid-run
        raise FileNotFoundError(f"fs.write: output directory does not exist: {parent}")
    cols = table.column_names()
    line_fn, header = _row_formatter(format, cols)
    lock = threading.Lock()
    # LAZY open (r5 exactly-once): opening "w" at graph build would truncate
    # a previous run's output BEFORE the persistence layer can restore the
    # snapshot write position; the handle opens on first write — or in
    # restore_sink, which rewinds the existing file to the snapshot cut
    state: dict[str, Any] = {"fh": None, "final_offset": None}

    def _ensure_open():
        if state["fh"] is None:
            fh = open(filename, "w", newline="")
            if header is not None:
                fh.write(header)
            state["fh"] = fh
        return state["fh"]

    def on_batch(batch: DeltaBatch, columns: list[str]) -> None:
        with lock:
            fh = _ensure_open()
            for _key, diff, row in batch.rows():
                fh.write(line_fn(row, batch.time, diff))
            fh.flush()

    def on_done() -> None:
        # on_end fires on the owning (worker-0) replica only
        with lock:
            fh = _ensure_open()  # a zero-row run still yields the (header) file
            if not fh.closed:
                fh.flush()
                # the at-close snapshot runs AFTER on_end: remember the final
                # position so sink_state doesn't report "nothing written" and
                # doom the next restart to truncating the completed output
                state["final_offset"] = fh.tell()
                fh.close()

    def sink_state() -> dict:
        """Durable write position at a quiesced tick boundary — snapshotted
        with the operator generation so restart rewinds to a consistent cut."""
        with lock:
            fh = state["fh"]
            if fh is None or fh.closed:
                return {"offset": state["final_offset"]}
            fh.flush()
            return {"offset": fh.tell()}

    def restore_sink(s: dict) -> None:
        with lock:
            if state["fh"] is not None:
                return  # already restored (other worker replicas share state)
            off = s.get("offset")
            if off is None or not os.path.exists(filename):
                return  # nothing had been written at the snapshot: fresh file
            size = os.path.getsize(filename)
            if off > size:
                # the snapshot says `off` bytes were durably written but the
                # file is shorter: it was externally truncated/replaced, and
                # the consumed input prefix is already compacted — recovery
                # cannot reconstruct it, so fail loudly instead of silently
                # NUL-padding a corrupt output
                raise RuntimeError(
                    f"fs.write exactly-once restore: {filename!r} is {size} "
                    f"bytes but the snapshot recorded {off}; the output file "
                    "was modified outside the pipeline — remove it and the "
                    "persistence storage to start fresh"
                )
            fh = open(filename, "r+", newline="")
            fh.truncate(off)
            fh.seek(off)
            state["fh"] = fh

    def factory() -> Node:
        from pathway_tpu.internals.logical import current_build

        ctx = current_build()
        # only global worker 0's replica owns the handle: a SOLO sink routes
        # every row there, and peer replicas (other workers/processes) must
        # not create-or-truncate the file from their own on_end
        owner = ctx is None or ctx.worker_index == 0
        return ops.CallbackOutputNode(
            cols,
            on_batch,
            on_done if owner else None,
            sink_state=sink_state if owner else None,
            restore_sink=restore_sink if owner else None,
            service_class=service_class,
        )

    LogicalNode(factory, [table._node], name=f"fs_write:{filename}")._register_as_output()


def _write_ledger(
    table: Table,
    filename: str,
    *,
    format: str,  # noqa: A002
    service_class: str = "interactive",
) -> None:
    """The fs sink re-expressed over the delivery ledger API: rows buffer as
    formatted lines, stage per epoch, and the FsDeliveryTransport appends them
    behind the offset sidecar at recovery points."""
    from pathway_tpu import delivery as _delivery

    parent = os.path.dirname(os.path.abspath(filename))
    if not os.path.isdir(parent):
        raise FileNotFoundError(f"fs.write: output directory does not exist: {parent}")
    cols = table.column_names()
    line_fn, header = _row_formatter(format, cols)
    transport = _delivery.FsDeliveryTransport(filename, header=header)
    writer = _delivery.LedgerWriter(f"fs.{filename}", transport)

    def on_batch(batch: DeltaBatch, columns: list[str]) -> None:
        for _key, diff, row in batch.rows():
            writer.append(0, line_fn(row, batch.time, diff))

    def factory() -> Node:
        node = ops.CallbackOutputNode(
            cols,
            on_batch,
            sink_state=writer.sink_state,
            restore_sink=writer.restore_sink,
            service_class=service_class,
        )
        node.delivery_writer = writer
        return node

    LogicalNode(factory, [table._node], name=f"fs_write:{filename}")._register_as_output()


def _row_formatter(format: str, cols: list[str]):  # noqa: A002
    """line(row, time, diff) -> str, shared by the solo and sharded writers."""
    if format == "csv":
        import io as _io

        def line(row, time, diff) -> str:
            buf = _io.StringIO()
            _csv.writer(buf).writerow(list(row) + [time, diff])
            return buf.getvalue()

        hbuf = _io.StringIO()
        _csv.writer(hbuf).writerow(cols + ["time", "diff"])
        return line, hbuf.getvalue()
    if format in ("json", "jsonlines"):
        from pathway_tpu.internals.json import Json

        def line(row, time, diff) -> str:
            rec = {}
            for c, v in zip(cols, row):
                if isinstance(v, Json):
                    v = v.value
                elif isinstance(v, np.generic):
                    v = v.item()
                elif isinstance(v, tuple):
                    v = list(v)
                rec[c] = v
            rec["time"] = time
            rec["diff"] = diff
            return _json.dumps(rec) + "\n"

        return line, None
    raise ValueError(f"unknown format {format!r}")


def _write_sharded(
    table: Table,
    filename: str,
    *,
    format: str,  # noqa: A002
    service_class: str = "interactive",
    **kwargs: Any,
) -> None:
    """Per-worker sink shards + ordered merge-commit (VERDICT r4 #2).

    Persistence (ISSUE 2 satellite, ADVICE r5): part files get the same
    lazy-open + per-part offset snapshot/restore hooks as the solo writer —
    each worker's replica snapshots ITS part's durable offset with the
    operator generation and a restart rewinds that part to the cut, so a
    kill mid-stream can no longer truncate previously-committed part rows.
    A restart AFTER the parts merge-committed (parts deleted) restores a
    ``merged`` marker instead: re-appending to a merged output is
    unsupported and raises a clear error rather than corrupting it."""
    import heapq

    cols = table.column_names()
    line_fn, header = _row_formatter(format, cols)
    lock = threading.Lock()
    state: dict[str, Any] = {
        "parts": {},
        "closed": set(),
        "n_workers": 1,
        "merged_done": False,
        "restored_merged": False,
    }

    def _merge() -> None:
        """All shards closed: merge parts into ``filename`` ordered by
        (time, worker), then remove them. Parts are time-ordered internally
        (ticks are monotonic), so a k-way stable merge suffices."""

        def part_rows(w: int, path: str):
            if format == "csv":
                # csv.reader handles quoted embedded newlines (a raw line scan
                # would split multi-physical-line records); re-serialize each
                # record so the merged file stays one valid csv stream
                import io as _io

                with open(path, newline="") as fh:
                    for i, rec in enumerate(_csv.reader(fh)):
                        if i == 0 or not rec:
                            continue  # per-part header
                        buf = _io.StringIO()
                        _csv.writer(buf).writerow(rec)
                        yield (int(rec[len(cols)]), w, buf.getvalue())
            else:
                with open(path) as fh:
                    for raw in fh:
                        if not raw.strip():
                            continue
                        yield (int(_json.loads(raw)["time"]), w, raw)

        parts = sorted(state["parts"].items())
        with open(filename, "w", newline="") as out:
            if header is not None:
                out.write(header)
            for _t, _w, raw in heapq.merge(
                *(part_rows(w, p) for w, p in parts), key=lambda r: (r[0], r[1])
            ):
                out.write(raw)
        for _w, p in parts:
            os.remove(p)

    def _check_stale_parts(n: int) -> None:
        """Part files from a previous run under a DIFFERENT worker count
        (elasticity satellite): ``part-<w>`` for w outside the current worker
        set would silently survive as stale output next to the live parts.
        Formatted part rows carry no keys, so remapping them by key range is
        impossible from the files alone — with the elasticity plane enabled
        the stale parts are removed (every row regenerates from the replayed
        input logs, re-routed by the new shard map); otherwise fail with a
        clear error naming the mismatch."""
        import glob as _glob

        stale = []
        # escape the sink path: a filename with glob metacharacters must not
        # silently disable the detection this guard exists for
        for p in _glob.glob(_glob.escape(filename) + ".part-*"):
            suffix = p.rsplit(".part-", 1)[1]
            if suffix.isdigit() and int(suffix) >= n:
                stale.append(p)
        if not stale:
            return
        from pathway_tpu import elastic as _elastic
        from pathway_tpu.internals.telemetry import record_event

        if _elastic.reshard_enabled():
            for p in stale:
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass  # another cluster process won the race
            record_event(
                "elastic.sink_parts_remapped",
                sink=filename,
                removed=len(stale),
                n_workers=n,
            )
            return
        old_n = max(int(p.rsplit(".part-", 1)[1]) for p in stale) + 1
        raise RuntimeError(
            f"fs.write(sharded=True) restore: found part file(s) "
            f"{sorted(os.path.basename(p) for p in stale)} from a run with "
            f"at least {old_n} workers, but this run has {n}; part rows "
            "carry no keys so they cannot be remapped by key range — "
            "restart with the original worker count, remove the stale parts "
            "and the persistence storage, or enable PATHWAY_ELASTIC to "
            "rebuild every part from the replayed input logs"
        )

    def factory() -> Node:
        from pathway_tpu.internals.logical import current_build

        ctx = current_build()
        w = ctx.worker_index if ctx is not None else 0
        n = ctx.n_workers if ctx is not None else 1
        part_path = f"{filename}.part-{w:04d}"
        with lock:
            state["parts"][w] = part_path
            state["n_workers"] = max(state["n_workers"], n)
            if not state.get("stale_checked"):
                state["stale_checked"] = True
                _check_stale_parts(n)
        # LAZY open (same rule as the solo writer): opening "w" at graph build
        # would truncate a previous run's part BEFORE restore_sink can rewind
        # it to the snapshot cut
        pstate: dict[str, Any] = {"fh": None, "final_offset": None}

        def _ensure_open():
            if pstate["fh"] is None:
                if state["restored_merged"]:
                    raise RuntimeError(
                        f"fs.write(sharded=True) restore: {filename!r} was "
                        "already merge-committed by the previous run; "
                        "appending new rows to a merged output is not "
                        "supported — remove the output file and the "
                        "persistence storage to start fresh"
                    )
                fh = open(part_path, "w", newline="")
                if header is not None:
                    fh.write(header)
                pstate["fh"] = fh
            return pstate["fh"]

        def on_batch(batch: DeltaBatch, columns: list[str]) -> None:
            fh = _ensure_open()
            for _key, diff, row in batch.rows():
                fh.write(line_fn(row, batch.time, diff))
            fh.flush()

        def sink_state() -> dict:
            """This part's durable offset at a quiesced tick boundary (or the
            merged marker once the parts were merge-committed)."""
            with lock:
                if state["merged_done"] or state["restored_merged"]:
                    return {"merged": True}
                fh = pstate["fh"]
                if fh is None or fh.closed:
                    return {"offset": pstate["final_offset"]}
                fh.flush()
                return {"offset": fh.tell()}

        def restore_sink(s: dict) -> None:
            with lock:
                if s.get("merged"):
                    state["restored_merged"] = True
                    return
                if pstate["fh"] is not None:
                    return
                off = s.get("offset")
                if off is None:
                    return  # nothing durably written at the snapshot: fresh part
                if not os.path.exists(part_path):
                    # the snapshot says this part held `off` bytes but the part
                    # is gone — parts are only ever removed by _merge(), so a
                    # crash landed between the merge-commit and the at-close
                    # snapshot. The merged output IS the completed run; treat
                    # it as merged (appending later raises the clear error)
                    # rather than silently re-merging only the replayed tail
                    # over it. A missing merged file too means outside
                    # interference — refuse.
                    if os.path.exists(filename):
                        state["restored_merged"] = True
                        return
                    raise RuntimeError(
                        f"fs.write(sharded=True) restore: {part_path!r} is "
                        f"missing but the snapshot recorded {off} bytes and "
                        f"no merged output {filename!r} exists; the files "
                        "were removed outside the pipeline — clear the "
                        "persistence storage to start fresh"
                    )
                size = os.path.getsize(part_path)
                if off > size:
                    raise RuntimeError(
                        f"fs.write(sharded=True) restore: {part_path!r} is "
                        f"{size} bytes but the snapshot recorded {off}; the "
                        "part file was modified outside the pipeline — remove "
                        "it and the persistence storage to start fresh"
                    )
                fh = open(part_path, "r+", newline="")
                fh.truncate(off)
                fh.seek(off)
                pstate["fh"] = fh

        def on_done() -> None:
            with lock:
                if state["restored_merged"]:
                    # previous run completed and merged; nothing new arrived
                    # (a write would have raised in _ensure_open)
                    state["closed"].add(w)
                    return
            fh = _ensure_open()  # a zero-row shard still yields a (header) part
            with lock:
                if not fh.closed:
                    fh.flush()
                    pstate["final_offset"] = fh.tell()
                    fh.close()
                state["closed"].add(w)
                # thread plane: the last shard to close merge-commits; a
                # cluster process only ever sees its local shards and leaves
                # the part files for the consumer
                if (
                    len(state["closed"]) == state["n_workers"]
                    and len(state["parts"]) == state["n_workers"]
                ):
                    _merge()
                    state["merged_done"] = True

        return ops.CallbackOutputNode(
            cols,
            on_batch,
            on_done,
            sharded=True,
            sink_state=sink_state,
            restore_sink=restore_sink,
            service_class=service_class,
        )

    LogicalNode(factory, [table._node], name=f"fs_write:{filename}")._register_as_output()
