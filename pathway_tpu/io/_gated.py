"""Shared dependency-gate helper for connectors whose client libraries are not in
this image (remaining gated modules: airbyte, sharepoint — deltalake/iceberg are
implemented against their open formats, gdrive against an injectable transport).
Each gated module keeps the reference's call signature and raises a clear
NotImplementedError."""

from __future__ import annotations


def gate(connector: str, requirement: str):
    def _raise(*args, **kwargs):
        raise NotImplementedError(
            f"pw.io.{connector} requires {requirement}, which is not available in "
            "this environment"
        )

    return _raise
