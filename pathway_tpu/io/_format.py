"""Parser / Formatter abstraction — the seam between raw connector payloads and
typed engine rows.

Role of the reference's ``src/connectors/data_format.rs``: ``Parser``
(``:246`` — DsvParser:763, IdentityParser:843, DebeziumMessageParser:1433,
JsonLinesParser:1565, TransparentParser:1671) turns a raw message into
``ParsedEvent::{Insert,Delete}``; ``Formatter`` (``:442`` — DsvFormatter:924,
SingleColumnFormatter:991, JsonLinesFormatter:1932, NullFormatter:1976) renders an
output diff row into sink payloads. Every connector composes one of each, so new
transports (Kafka, S3, sockets…) cost only a Reader/Writer, and new encodings cost
only a Parser/Formatter.
"""

from __future__ import annotations

import csv as _csv
import io as _io
import json as _json
from dataclasses import dataclass, field
from typing import Any, Iterable

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod


# --------------------------------------------------------------------------- events
@dataclass
class RawMessage:
    """One transport-level message (Kafka record, file line, socket frame)."""

    value: bytes | str
    key: bytes | str | None = None
    metadata: dict[str, Any] = field(default_factory=dict)


@dataclass
class ParsedEvent:
    """Typed row delta produced by a parser (``ParsedEvent::{Insert,Delete}``,
    ``data_format.rs:93``). ``values`` follow the parser schema's column order.
    ``tombstone`` marks a Debezium null-payload key-death record: ``values``
    carry only the primary-key fields (from the message key) and consumers in
    upsert sessions translate it to a keyed delete, while diff-native sessions
    ignore it (the preceding ``op: d`` event already retracted the row)."""

    values: tuple
    diff: int = 1
    tombstone: bool = False


def coerce_scalar(tok: Any, d: dt.DType) -> Any:
    """Parse one textual token into the schema dtype; parse failures become the
    ERROR value (``Value::Error`` poisoning, not an abort)."""
    d = dt.unoptionalize(d)
    try:
        if tok is None:
            return None
        if d == dt.INT:
            return int(tok)
        if d == dt.FLOAT:
            return float(tok)
        if d == dt.BOOL:
            if isinstance(tok, bool):
                return tok
            return str(tok).strip().lower() in ("true", "1", "yes", "t")
        if d == dt.JSON:
            from pathway_tpu.internals.json import Json

            if isinstance(tok, Json):
                return tok
            if isinstance(tok, (dict, list, int, float, bool)):
                return Json(tok)
            return Json(_json.loads(tok))
        if d == dt.BYTES:
            return tok.encode() if isinstance(tok, str) else tok
        if d == dt.STR and isinstance(tok, bytes):
            return tok.decode(errors="replace")
        return tok
    except (ValueError, TypeError):
        from pathway_tpu.internals.errors import ERROR

        return ERROR


def _as_text(raw: bytes | str) -> str:
    return raw.decode(errors="replace") if isinstance(raw, bytes) else raw


# --------------------------------------------------------------------------- parsers
class Parser:
    """Turns one RawMessage into typed ParsedEvents."""

    def __init__(self, schema: schema_mod.SchemaMetaclass):
        self.schema = schema
        self.columns = schema.column_names()
        self.dtypes = schema.dtypes()

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        raise NotImplementedError

    def _row_from_mapping(self, rec: dict) -> tuple:
        return tuple(coerce_scalar(rec.get(c), self.dtypes[c]) for c in self.columns)


class DsvParser(Parser):
    """Delimiter-separated values; one message = one record. Fields follow the
    schema's column order (Kafka-style headerless lines)."""

    def __init__(self, schema, delimiter: str = ","):
        super().__init__(schema)
        self.delimiter = delimiter

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        text = _as_text(message.value)
        reader = _csv.reader(_io.StringIO(text), delimiter=self.delimiter)
        out = []
        for rec in reader:
            if not rec:
                continue
            out.append(
                ParsedEvent(
                    tuple(
                        coerce_scalar(tok, self.dtypes[c])
                        for tok, c in zip(rec, self.columns)
                    )
                )
            )
        return out


class JsonLinesParser(Parser):
    """One JSON object per message (or per line of a multi-line message)."""

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        out = []
        for line in _as_text(message.value).splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                rec = _json.loads(line)
            except ValueError:
                from pathway_tpu.internals.errors import ERROR

                out.append(ParsedEvent(tuple(ERROR for _ in self.columns)))
                continue
            out.append(ParsedEvent(self._row_from_mapping(rec)))
        return out


class IdentityParser(Parser):
    """Raw payload into the single ``data`` column (plaintext/binary streams)."""

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        (col,) = self.columns
        return [ParsedEvent((coerce_scalar(message.value, self.dtypes[col]),))]


class TransparentParser(Parser):
    """Values already arrive as tuples in schema order (in-process sources)."""

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        return [ParsedEvent(tuple(message.value))]


def rows_from_bytes(data: bytes, fmt: str, schema) -> list[tuple]:
    """Decode one whole payload (file / object) into schema-ordered rows —
    the ONE per-format recipe shared by the fs and s3 connectors so format
    semantics (csv coercion, JSON wrapping) cannot drift between them."""
    cols = schema.column_names()
    dtypes = schema.dtypes()
    if fmt == "binary":
        return [(data,)]
    text = data.decode(errors="replace")
    if fmt in ("plaintext_by_file", "plaintext_by_object"):
        return [(text,)]
    if fmt == "plaintext":
        return [(line,) for line in text.splitlines()]
    if fmt == "csv":
        rows = []
        for rec in _csv.DictReader(_io.StringIO(text)):
            rows.append(tuple(coerce_scalar(rec.get(c, ""), dtypes[c]) for c in cols))
        return rows
    if fmt in ("json", "jsonlines"):
        from pathway_tpu.internals.json import Json

        rows = []
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            rec = _json.loads(line)
            row = []
            for c in cols:
                v = rec.get(c)
                d = dt.unoptionalize(dtypes[c])
                if d == dt.JSON and not isinstance(v, Json):
                    v = Json(v)
                row.append(v)
            rows.append(tuple(row))
        return rows
    raise ValueError(f"unknown format {fmt!r}")


class DebeziumMessageParser(Parser):
    """CDC envelopes: ``{"payload": {"op": c|r|u|d, "before": …, "after": …}}``
    (reference ``DebeziumMessageParser:1433``, standard + MongoDB dialects).

    All four ops are handled: ``c``/``r`` insert ``after``; ``u`` retracts
    ``before`` and inserts ``after``; ``d`` retracts ``before``. Messages may
    arrive with or without the Connect ``{"schema": …, "payload": …}`` wrapper
    (both key and value sides). A null value / ``"payload": null`` is the
    Debezium log-compaction tombstone: with ``tombstones=True`` it parses into
    a pk-only event (pk fields unwrapped from the message key, ``diff=-1``,
    ``tombstone=True``) that upsert consumers turn into a keyed delete;
    with the default ``tombstones=False`` it is silently skipped — diff-native
    consumers already saw the retraction in the preceding ``op: d`` event."""

    def __init__(self, schema, tombstones: bool = False):
        super().__init__(schema)
        self.tombstones = tombstones

    @staticmethod
    def _unwrap(rec):
        """Strip the Kafka Connect schema block when present."""
        if isinstance(rec, dict) and "payload" in rec:
            return rec["payload"]
        return rec

    def _tombstone(self, message: RawMessage) -> list[ParsedEvent]:
        if not self.tombstones or message.key is None:
            return []
        try:
            krec = _json.loads(_as_text(message.key))
        except ValueError:
            return []
        kpayload = self._unwrap(krec)
        if not isinstance(kpayload, dict):
            return []
        return [
            ParsedEvent(self._row_from_mapping(kpayload), diff=-1, tombstone=True)
        ]

    def parse(self, message: RawMessage) -> list[ParsedEvent]:
        text = _as_text(message.value).strip() if message.value is not None else ""
        if not text or text == "null":
            return self._tombstone(message)
        rec = _json.loads(text)
        payload = self._unwrap(rec)
        if payload is None:
            return self._tombstone(message)
        op = payload.get("op", "c")
        before, after = payload.get("before"), payload.get("after")
        if isinstance(before, str):  # MongoDB dialect ships embedded JSON strings
            before = _json.loads(before)
        if isinstance(after, str):
            after = _json.loads(after)
        out = []
        if op in ("d", "u") and before is not None:
            out.append(ParsedEvent(self._row_from_mapping(before), diff=-1))
        if op in ("c", "r", "u") and after is not None:
            out.append(ParsedEvent(self._row_from_mapping(after), diff=1))
        return out


# ------------------------------------------------------------------------ formatters
class Formatter:
    """Renders one output diff row into a sink payload."""

    def __init__(self, columns: list[str]):
        self.columns = columns

    def format(self, key: int, row: tuple, time: int, diff: int) -> bytes:
        raise NotImplementedError


def _plain(v: Any) -> Any:
    import numpy as np

    from pathway_tpu.internals.json import Json

    if isinstance(v, Json):
        return v.value
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, tuple):
        return [_plain(x) for x in v]
    if isinstance(v, bytes):
        return v.decode(errors="replace")
    return v


class JsonLinesFormatter(Formatter):
    def format(self, key: int, row: tuple, time: int, diff: int) -> bytes:
        rec = {c: _plain(v) for c, v in zip(self.columns, row)}
        rec["time"] = time
        rec["diff"] = diff
        return _json.dumps(rec).encode()


class DsvFormatter(Formatter):
    def __init__(self, columns: list[str], delimiter: str = ","):
        super().__init__(columns)
        self.delimiter = delimiter

    def format(self, key: int, row: tuple, time: int, diff: int) -> bytes:
        buf = _io.StringIO()
        w = _csv.writer(buf, delimiter=self.delimiter)
        w.writerow([_plain(v) for v in row] + [time, diff])
        return buf.getvalue().rstrip("\r\n").encode()


class SingleColumnFormatter(Formatter):
    """Emits exactly one column's value as the payload."""

    def __init__(self, columns: list[str], column: str):
        super().__init__(columns)
        self.index = columns.index(column)

    def format(self, key: int, row: tuple, time: int, diff: int) -> bytes:
        v = row[self.index]
        if isinstance(v, bytes):
            return v
        return str(_plain(v)).encode()


class NullFormatter(Formatter):
    def format(self, key: int, row: tuple, time: int, diff: int) -> bytes:
        return b""


# ------------------------------------------------------------------------- registry
def parser_for(
    format: str,  # noqa: A002
    schema: schema_mod.SchemaMetaclass,
    **kwargs: Any,
) -> Parser:
    if format in ("csv", "dsv"):
        return DsvParser(schema, delimiter=kwargs.get("delimiter", ","))
    if format in ("json", "jsonlines"):
        return JsonLinesParser(schema)
    if format in ("plaintext", "raw", "binary", "identity"):
        return IdentityParser(schema)
    if format == "debezium":
        return DebeziumMessageParser(schema)
    raise ValueError(f"unknown input format {format!r}")


def formatter_for(format: str, columns: list[str], **kwargs: Any) -> Formatter:  # noqa: A002
    if format in ("csv", "dsv"):
        return DsvFormatter(columns, delimiter=kwargs.get("delimiter", ","))
    if format in ("json", "jsonlines"):
        return JsonLinesFormatter(columns)
    if format in ("plaintext", "raw", "single_column"):
        return SingleColumnFormatter(columns, kwargs.get("column", columns[0]))
    if format == "null":
        return NullFormatter(columns)
    raise ValueError(f"unknown output format {format!r}")
