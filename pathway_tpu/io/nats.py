"""NATS connector (reference ``python/pathway/io/nats``): subscribe a topic
into a table (raw / plaintext / json formats) and publish a table's change
stream.

The ``nats-py`` client is asyncio-based and not in this image; a
``client_factory`` kwarg injects any object exposing the small synchronous
surface the connector uses (``connect(uri) -> conn`` with
``subscribe(topic) -> iterator of payload bytes`` / ``publish(topic, bytes)``
/ ``close()``) — CI drives it with an in-memory fake. When ``nats-py`` IS
importable, the real client is adapted onto that surface with a private
asyncio loop per connector thread."""

from __future__ import annotations

import queue as _queue
import time as _time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import parser_for


class _NatsPyAdapter:
    """nats-py (asyncio) → the connector's synchronous client surface."""

    def connect(self, uri: str):
        import asyncio
        import threading

        import nats  # gated import

        loop = asyncio.new_event_loop()
        threading.Thread(target=loop.run_forever, daemon=True).start()

        def call(coro):
            import asyncio as _a

            return _a.run_coroutine_threadsafe(coro, loop).result(30)

        nc = call(nats.connect(uri))

        class _Conn:
            def subscribe(self, topic):
                q: _queue.Queue = _queue.Queue()

                async def cb(msg):
                    q.put(msg.data)

                call(nc.subscribe(topic, cb=cb))

                def it():
                    while True:
                        try:
                            yield q.get(timeout=0.1)
                        except _queue.Empty:
                            yield None

                return it()

            def publish(self, topic, payload: bytes):
                call(nc.publish(topic, payload))

            def close(self):
                call(nc.drain())
                loop.call_soon_threadsafe(loop.stop)

        return _Conn()


def _client(kwargs: dict):
    factory = kwargs.pop("client_factory", None)
    if factory is not None:
        return factory
    try:
        import nats  # noqa: F401
    except ImportError:
        raise NotImplementedError(
            "pw.io.nats requires the nats-py client (or a client_factory= "
            "kwarg), which is not available in this environment"
        ) from None
    return _NatsPyAdapter()


def read(
    uri: str,
    topic: str,
    *,
    schema: schema_mod.SchemaMetaclass | None = None,
    format: str = "raw",  # noqa: A002
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    factory = _client(kwargs)
    if format == "raw":
        schema = schema_mod.schema_from_types(data=bytes)
        parser = None
    elif format == "plaintext":
        schema = schema_mod.schema_from_types(data=str)
        parser = None
    elif format == "json":
        if schema is None:
            raise ValueError("schema required for the json format")
        parser = parser_for("json", schema)
    else:
        raise ValueError(f"unknown NATS format {format!r}")

    from pathway_tpu.io._format import RawMessage
    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    fmt = format

    class _NatsSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._stop = False

        def run(self) -> None:
            conn = factory.connect(uri)
            try:
                for payload in conn.subscribe(topic):
                    if self._stop:
                        return
                    if payload is None:
                        continue
                    if fmt == "raw":
                        self.next(data=bytes(payload))
                    elif fmt == "plaintext":
                        self.next(
                            data=payload.decode(errors="replace")
                            if isinstance(payload, bytes)
                            else str(payload)
                        )
                    else:
                        for ev in parser.parse(RawMessage(value=payload)):
                            self._push(ev.values, diff=ev.diff)
            finally:
                try:
                    conn.close()
                except Exception:
                    pass

        def on_stop(self) -> None:
            self._stop = True

    return py_read(_NatsSubject(), schema=schema, name=name or f"nats:{topic}")


def write(
    table: Table,
    uri: str,
    topic: str,
    *,
    format: str = "json",  # noqa: A002
    name: str | None = None,
    **kwargs: Any,
) -> None:
    from pathway_tpu.io._format import formatter_for

    if format not in ("json", "raw", "plaintext"):
        raise ValueError(f"unknown NATS format {format!r}")
    factory = _client(kwargs)
    cols = table.column_names()
    if format != "json" and len(cols) != 1:
        raise ValueError(
            f"NATS {format!r} write requires a single-column table; "
            f"got {len(cols)} columns (use format='json')"
        )
    fmt = formatter_for("json", cols) if format == "json" else None
    conn_holder: dict = {}

    def on_batch(batch, columns) -> None:
        conn = conn_holder.get("c")
        if conn is None:
            conn = conn_holder["c"] = factory.connect(uri)
        for key, diff, row in batch.rows():
            if fmt is not None:
                payload = fmt.format(int(key), row, batch.time, diff)
                if not isinstance(payload, bytes):
                    payload = payload.encode()
            else:  # raw/plaintext single column
                v = row[0]
                payload = v if isinstance(v, bytes) else str(v).encode()
            conn.publish(topic, payload)

    def on_done() -> None:
        conn = conn_holder.pop("c", None)
        if conn is not None:
            # drain buffered publishes (the real nats-py adapter's close()
            # flushes; without it tail messages die in the transport buffer)
            conn.close()

    from pathway_tpu.engine import operators as ops
    from pathway_tpu.internals.logical import LogicalNode

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch, on_done),
        [table._node],
        name=name or f"nats_write:{topic}",
    )._register_as_output()
