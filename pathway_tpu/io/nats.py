"""Gated connector: reference `python/pathway/io/nats`. See _gated.py."""

from pathway_tpu.io._gated import gate

read = gate("nats", "the nats-py client")
write = gate("nats", "the nats-py client")
