"""Minimal pure-Python Avro Object Container File codec.

Backs the Iceberg connector (``io/iceberg.py``): Iceberg manifests and
manifest lists are Avro files (reference ``IcebergBatchWriter`` writes them
through iceberg-rust, ``/root/reference/src/connectors/data_lake/iceberg.rs:208``;
no Avro library ships on this image). Implements the container spec
(magic ``Obj\\x01``, metadata map with schema JSON + null codec, sync-marker
delimited blocks) and the binary encoding for the types Iceberg metadata
needs: null, boolean, int/long (zigzag varint), float, double, string, bytes,
fixed, records, arrays, maps, and ``[null, X]`` unions.
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any

_MAGIC = b"Obj\x01"


# ----------------------------------------------------------------- encoding
def _zigzag(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(buf: io.BytesIO, n: int) -> None:
    n = _zigzag(n) & 0xFFFFFFFFFFFFFFFF
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            buf.write(bytes([b | 0x80]))
        else:
            buf.write(bytes([b]))
            return


def _read_long(buf) -> int:
    shift = 0
    acc = 0
    while True:
        (b,) = buf.read(1)
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return _unzigzag(acc)
        shift += 7


def _write_bytes(buf: io.BytesIO, b: bytes) -> None:
    _write_long(buf, len(b))
    buf.write(b)


def _read_bytes(buf) -> bytes:
    return buf.read(_read_long(buf))


def _branch_for(value: Any, branches: list) -> int:
    """Union branch index for a value (null vs the single non-null branch —
    the only union shape Iceberg metadata uses)."""
    for i, b in enumerate(branches):
        if value is None and b == "null":
            return i
        if value is not None and b != "null":
            return i
    raise ValueError(f"no union branch for {value!r} in {branches}")


def write_datum(buf: io.BytesIO, schema: Any, value: Any) -> None:
    if isinstance(schema, list):  # union
        i = _branch_for(value, schema)
        _write_long(buf, i)
        return write_datum(buf, schema[i], value)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                write_datum(
                    buf, f["type"], value.get(f["name"]) if value else None
                )
            return
        if t == "array":
            items = value or []
            if items:
                _write_long(buf, len(items))
                for it in items:
                    write_datum(buf, schema["items"], it)
            _write_long(buf, 0)
            return
        if t == "map":
            entries = value or {}
            if entries:
                _write_long(buf, len(entries))
                for k, v in entries.items():
                    _write_bytes(buf, str(k).encode())
                    write_datum(buf, schema["values"], v)
            _write_long(buf, 0)
            return
        if t == "fixed":
            assert len(value) == schema["size"]
            buf.write(value)
            return
        return write_datum(buf, t, value)  # {"type": "string"} primitive form
    if schema == "null":
        return
    if schema == "boolean":
        buf.write(b"\x01" if value else b"\x00")
        return
    if schema in ("int", "long"):
        _write_long(buf, int(value))
        return
    if schema == "float":
        buf.write(struct.pack("<f", float(value)))
        return
    if schema == "double":
        buf.write(struct.pack("<d", float(value)))
        return
    if schema == "string":
        _write_bytes(buf, str(value).encode())
        return
    if schema == "bytes":
        _write_bytes(buf, bytes(value))
        return
    raise ValueError(f"unsupported avro type {schema!r}")


def read_datum(buf, schema: Any) -> Any:
    if isinstance(schema, list):
        return read_datum(buf, schema[_read_long(buf)])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: read_datum(buf, f["type"]) for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    return out
                if n < 0:  # block with byte size prefix
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    out.append(read_datum(buf, schema["items"]))
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    return out
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode()
                    out[k] = read_datum(buf, schema["values"])
        if t == "fixed":
            return buf.read(schema["size"])
        return read_datum(buf, t)
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "string":
        return _read_bytes(buf).decode()
    if schema == "bytes":
        return _read_bytes(buf)
    raise ValueError(f"unsupported avro type {schema!r}")


# ---------------------------------------------------------------- container
def write_container(
    path: str, schema: dict, records: list[dict], metadata: dict | None = None
) -> None:
    """One-block Avro Object Container File (null codec)."""
    body = io.BytesIO()
    for rec in records:
        write_datum(body, schema, rec)
    payload = body.getvalue()
    sync = os.urandom(16)
    out = io.BytesIO()
    out.write(_MAGIC)
    meta = {"avro.schema": json.dumps(schema), "avro.codec": "null"}
    for k, v in (metadata or {}).items():
        meta[k] = v
    _write_long(out, len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode())
        _write_bytes(out, v.encode() if isinstance(v, str) else v)
    _write_long(out, 0)
    out.write(sync)
    _write_long(out, len(records))
    _write_long(out, len(payload))
    out.write(payload)
    out.write(sync)
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "wb") as fh:
        fh.write(out.getvalue())
    os.replace(tmp, path)


def read_container(path: str) -> tuple[dict, list[dict]]:
    """→ (writer schema, records)."""
    with open(path, "rb") as fh:
        raw = fh.read()
    buf = io.BytesIO(raw)
    assert buf.read(4) == _MAGIC, f"not an avro container: {path}"
    meta: dict[str, bytes] = {}
    while True:
        n = _read_long(buf)
        if n == 0:
            break
        if n < 0:
            _read_long(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null")
    if codec not in (b"null", "null"):
        raise NotImplementedError(f"avro codec {codec!r} unsupported")
    sync = buf.read(16)
    records: list[dict] = []
    while buf.tell() < len(raw):
        count = _read_long(buf)
        _size = _read_long(buf)
        for _ in range(count):
            records.append(read_datum(buf, schema))
        assert buf.read(16) == sync, f"sync marker mismatch in {path}"
    return schema, records
