"""Slack writer (reference: ``python/pathway/io/slack``): posts one message per
positive output diff to a channel via chat.postMessage."""

from __future__ import annotations

from typing import Any

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import SingleColumnFormatter


def send_alerts(alerts: Table, slack_channel_id: str, slack_token: str, **kwargs: Any) -> None:
    import requests

    cols = alerts.column_names()
    fmt = SingleColumnFormatter(cols, cols[0])

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            if diff <= 0:
                continue
            requests.post(
                "https://slack.com/api/chat.postMessage",
                headers={"Authorization": f"Bearer {slack_token}"},
                json={
                    "channel": slack_channel_id,
                    "text": fmt.format(int(key), row, batch.time, diff).decode(),
                },
            )

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [alerts._node],
        name=f"slack:{slack_channel_id}",
    )._register_as_output()
