"""Slack writer (reference: ``python/pathway/io/slack``): posts one message per
positive output diff to a channel via chat.postMessage. The shared
:func:`post_message` helper is also the delivery path of the health plane's
Slack notification sink (``observability/alerts.SlackSink``)."""

from __future__ import annotations

from typing import Any, Callable

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import SingleColumnFormatter


def post_message(
    channel: str,
    token: str,
    text: str,
    transport: Callable[[str, dict, dict], Any] | None = None,
) -> None:
    """One ``chat.postMessage`` call. ``transport(url, headers, json_body)``
    is injectable so tests (and the alert sink's fake-transport test) never
    touch the network."""
    url = "https://slack.com/api/chat.postMessage"
    headers = {"Authorization": f"Bearer {token}"}
    body = {"channel": channel, "text": text}
    if transport is not None:
        transport(url, headers, body)
        return
    import requests

    requests.post(url, headers=headers, json=body)


def send_alerts(alerts: Table, slack_channel_id: str, slack_token: str, **kwargs: Any) -> None:
    cols = alerts.column_names()
    fmt = SingleColumnFormatter(cols, cols[0])
    transport = kwargs.get("_transport")

    def on_batch(batch, columns) -> None:
        for key, diff, row in batch.rows():
            if diff <= 0:
                continue
            post_message(
                slack_channel_id,
                slack_token,
                fmt.format(int(key), row, batch.time, diff).decode(),
                transport=transport,
            )

    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, on_batch),
        [alerts._node],
        name=f"slack:{slack_channel_id}",
    )._register_as_output()
