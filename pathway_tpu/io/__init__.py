"""I/O connectors (reference: ``python/pathway/io/`` — 30 modules, each building a
DataStorage/DataFormat descriptor; here each module wires source/sink engine nodes
directly)."""

from pathway_tpu.io import csv, fs, http, jsonlines, plaintext, python
from pathway_tpu.io._subscribe import subscribe
from pathway_tpu.io.null import write as _null_write

__all__ = ["csv", "fs", "http", "jsonlines", "plaintext", "python", "subscribe", "null"]

from pathway_tpu.io import null  # noqa: E402
