"""I/O connectors (reference: ``python/pathway/io/`` — 30 modules, each building a
DataStorage/DataFormat descriptor; here each module wires source/sink engine nodes
directly)."""

from pathway_tpu.io import (
    airbyte,
    bigquery,
    csv,
    debezium,
    deltalake,
    elasticsearch,
    fs,
    gdrive,
    http,
    iceberg,
    jsonlines,
    kafka,
    logstash,
    minio,
    mongodb,
    nats,
    plaintext,
    postgres,
    pubsub,
    pyfilesystem,
    python,
    s3,
    s3_csv,
    slack,
    sqlite,
)
from pathway_tpu.io import kafka as redpanda  # reference alias: io/redpanda = io/kafka
from pathway_tpu.io._subscribe import subscribe
from pathway_tpu.io.null import write as _null_write

__all__ = [
    "airbyte",
    "bigquery",
    "csv",
    "debezium",
    "deltalake",
    "elasticsearch",
    "fs",
    "gdrive",
    "http",
    "iceberg",
    "jsonlines",
    "kafka",
    "logstash",
    "minio",
    "mongodb",
    "nats",
    "plaintext",
    "postgres",
    "pubsub",
    "pyfilesystem",
    "python",
    "redpanda",
    "s3",
    "s3_csv",
    "slack",
    "sqlite",
    "subscribe",
    "null",
]

from pathway_tpu.io import null  # noqa: E402
