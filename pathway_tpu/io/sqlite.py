"""SQLite connector (reference: ``SqliteReader``, ``src/connectors/data_storage.rs:1707``
+ ``python/pathway/io/sqlite``).

``mode="static"`` snapshots the table once. ``mode="streaming"`` polls SQLite's
``data_version`` pragma and re-scans on change, emitting upsert deltas keyed by the
schema's primary keys — the reference reader's snapshot-diff behavior.
"""

from __future__ import annotations

import sqlite3
import threading
import time as _time
from typing import Any

from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.table import Table
from pathway_tpu.io._format import coerce_scalar


def _scan(path: str, table_name: str, schema: schema_mod.SchemaMetaclass) -> list[tuple]:
    cols = schema.column_names()
    dtypes = schema.dtypes()
    con = sqlite3.connect(path)
    try:
        cur = con.execute(
            f"SELECT {', '.join(cols)} FROM {table_name}"  # noqa: S608 — names from schema
        )
        return [
            tuple(coerce_scalar(v, dtypes[c]) for v, c in zip(row, cols))
            for row in cur.fetchall()
        ]
    finally:
        con.close()


def read(
    path: str,
    table_name: str,
    schema: schema_mod.SchemaMetaclass,
    *,
    mode: str = "streaming",
    poll_interval: float = 0.2,
    autocommit_duration_ms: int | None = None,
    name: str | None = None,
    **kwargs: Any,
) -> Table:
    if mode == "static":
        from pathway_tpu.io.fs import _keys_for
        from pathway_tpu.internals.table import table_from_static_data

        rows = _scan(path, table_name, schema)
        keys = _keys_for(rows, schema, salt=hash(table_name) & 0xFFFF)
        return table_from_static_data(keys, rows, schema)

    if not schema.primary_key_columns():
        raise ValueError("sqlite streaming mode requires a schema with primary keys")

    from pathway_tpu.io.python import ConnectorSubject, read as py_read

    class _SqliteSubject(ConnectorSubject):
        def __init__(self) -> None:
            super().__init__()
            self._stop = False
            self._snapshot: dict[tuple, tuple] = {}
            self.sync_lock = threading.Lock()

        @property
        def _session_type(self) -> str:
            return "upsert"

        def _emit_diff(self) -> None:
            pk_idx = [schema.column_names().index(c) for c in schema.primary_key_columns()]
            current = {tuple(r[i] for i in pk_idx): r for r in _scan(path, table_name, schema)}
            with self.sync_lock:
                for pk, row in current.items():
                    if self._snapshot.get(pk) != row:
                        self._push(row, diff=1)  # upsert session retracts the old row
                for pk, row in self._snapshot.items():
                    if pk not in current:
                        self._node.push(self._key_of(row), None, -1)
                self._snapshot = current

        def run(self) -> None:
            # re-scan each poll; the keyed snapshot diff emits deltas only for
            # changed rows (PRAGMA data_version is per-connection, so a fresh
            # connection per poll cannot use it as a change signal)
            while not self._stop:
                try:
                    self._emit_diff()
                except sqlite3.Error:
                    pass  # writer mid-transaction; retry next poll
                _time.sleep(poll_interval)

        def on_stop(self) -> None:
            self._stop = True

    return py_read(
        _SqliteSubject(), schema=schema, name=name or f"sqlite:{table_name}"
    )
