"""Null sink (reference: ``python/pathway/io/null`` / Rust NullWriter) — forces
materialization without writing anywhere."""

from __future__ import annotations

from pathway_tpu.engine import operators as ops
from pathway_tpu.internals.logical import LogicalNode


def write(table) -> None:
    cols = table.column_names()
    LogicalNode(
        lambda: ops.CallbackOutputNode(cols, lambda batch, columns: None),
        [table._node],
        name="null_write",
    )._register_as_output()
