"""Hybrid index: reciprocal-rank fusion over sub-indexes
(reference ``stdlib/indexing/hybrid_index.py:14``).

Each sub-index answers the query independently; results fuse by
``score = Σ 1 / (k + rank_i)`` (RRF, k=60 like the reference default).
"""

from __future__ import annotations

from pathway_tpu.stdlib.indexing.data_index import InnerIndex


class HybridIndex(InnerIndex):
    """Reply-level reciprocal-rank fusion: each sub-index answers independently
    (with its own representation — BM25 over text, KNN over embeddings), then the
    (doc, rank) lists fuse. Mirrors the reference's HybridIndex semantics."""

    def __init__(self, inner_indexes: list[InnerIndex], *, k: float = 60.0):
        if not inner_indexes:
            raise ValueError("HybridIndex needs at least one inner index")
        self.inner_indexes = inner_indexes
        self.k = k
        first = inner_indexes[0]
        self.data_column = first.data_column
        self.data_table = first.data_table
        self.metadata_column = first.metadata_column

    def _raw_reply(self, query_column, number_of_matches, metadata_filter, as_of_now):
        import pathway_tpu as pw
        from pathway_tpu.internals import dtype as dt
        from pathway_tpu.stdlib.indexing.data_index import _INDEX_REPLY

        replies = [
            ix._raw_reply(query_column, number_of_matches, metadata_filter, as_of_now)
            for ix in self.inner_indexes
        ]
        base = replies[0]
        cols = {"__r0": base[_INDEX_REPLY]}
        for i, r in enumerate(replies[1:], 1):
            cols[f"__r{i}"] = r.with_universe_of(base)[_INDEX_REPLY]
        # per-query match limit: materialize k on the query table and carry it
        # alongside the replies (as-of-now replies cover every query → same keys)
        qtable = query_column.table
        if isinstance(number_of_matches, int):
            cols["__k"] = number_of_matches
        else:
            qk = qtable.select(__k=number_of_matches)
            cols["__k"] = qk.with_universe_of(base)["__k"]
        merged = base.select(**cols)
        rrf_k = self.k
        n = len(replies)

        def fuse(limit, *reply_lists):
            fused: dict = {}
            for lst in reply_lists:
                for rank, (key, _s) in enumerate(lst or ()):
                    fused[key] = fused.get(key, 0.0) + 1.0 / (rrf_k + rank + 1)
            from pathway_tpu.internals.keys import tie_order

            ranked = sorted(fused.items(), key=lambda kv: (-kv[1], tie_order(kv[0])))
            return tuple(ranked[: int(limit)])

        return merged.select(
            **{
                _INDEX_REPLY: pw.apply_with_type(
                    fuse, dt.ANY, merged["__k"], *[merged[f"__r{i}"] for i in range(n)]
                )
            }
        )

    def query(self, query_column, *, number_of_matches=3, metadata_filter=None):
        return self._raw_reply(query_column, number_of_matches, metadata_filter, False)

    def query_as_of_now(self, query_column, *, number_of_matches=3, metadata_filter=None):
        return self._raw_reply(query_column, number_of_matches, metadata_filter, True)
