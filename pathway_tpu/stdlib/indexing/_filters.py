"""Minimal metadata-filter evaluator.

The reference filters index candidates with JMESPath boolean queries
(``src/external_integration/mod.rs:373`` via the jmespath crate;
``DocumentStore.merge_filters`` generates expressions like
``contains(path, 'foo')``, ``globmatch('*.md', path)``, combined with ``&&``/``||``).
jmespath isn't available in this image, so this module evaluates the subset those
call sites actually produce: field paths, string/number literals, ``==``/``!=``,
``contains(a, b)``, ``globmatch(pattern, path)``, ``starts_with``/``ends_with``,
parentheses, ``&&``/``||``/``!``.
"""

from __future__ import annotations

import fnmatch
import re
from typing import Any, Callable

_TOKEN = re.compile(
    r"""\s*(?:
        (?P<lpar>\()|(?P<rpar>\))|
        (?P<and>&&)|(?P<or>\|\|)|(?P<not>!(?!=))|
        (?P<eq>==)|(?P<ne>!=)|
        (?P<comma>,)|
        (?P<str>'(?:[^'\\]|\\.)*'|`[^`]*`|"(?:[^"\\]|\\.)*")|
        (?P<num>-?\d+(?:\.\d+)?)|
        (?P<name>[A-Za-z_][A-Za-z0-9_.]*)
    )""",
    re.VERBOSE,
)

_FUNCS: dict[str, Callable[..., Any]] = {
    "contains": lambda a, b: (b in a) if a is not None else False,
    "globmatch": lambda pat, s: fnmatch.fnmatch(str(s or ""), str(pat)),
    "starts_with": lambda a, b: str(a or "").startswith(str(b)),
    "ends_with": lambda a, b: str(a or "").endswith(str(b)),
}


def _tokenize(src: str) -> list[tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if m is None:
            if src[pos:].strip() == "":
                break
            raise ValueError(f"bad filter syntax at {src[pos:]!r}")
        pos = m.end()
        for kind, val in m.groupdict().items():
            if val is not None:
                out.append((kind, val))
                break
    return out


class _Parser:
    def __init__(self, tokens: list[tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def eat(self, kind=None):
        tok = self.peek()
        if kind is not None and tok[0] != kind:
            raise ValueError(f"expected {kind}, got {tok}")
        self.i += 1
        return tok

    def parse_or(self):
        node = self.parse_and()
        while self.peek()[0] == "or":
            self.eat()
            rhs = self.parse_and()
            node = ("or", node, rhs)
        return node

    def parse_and(self):
        node = self.parse_not()
        while self.peek()[0] == "and":
            self.eat()
            rhs = self.parse_not()
            node = ("and", node, rhs)
        return node

    def parse_not(self):
        if self.peek()[0] == "not":
            self.eat()
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        node = self.parse_atom()
        if self.peek()[0] in ("eq", "ne"):
            op = self.eat()[0]
            rhs = self.parse_atom()
            node = (op, node, rhs)
        return node

    def parse_atom(self):
        kind, val = self.peek()
        if kind == "lpar":
            self.eat()
            node = self.parse_or()
            self.eat("rpar")
            return node
        if kind == "str":
            self.eat()
            body = val[1:-1]
            if "\\" in body:
                body = re.sub(r"\\(.)", r"\1", body)
            return ("lit", body)
        if kind == "num":
            self.eat()
            return ("lit", float(val) if "." in val else int(val))
        if kind == "name":
            self.eat()
            if self.peek()[0] == "lpar":  # function call
                self.eat()
                args = []
                while self.peek()[0] != "rpar":
                    args.append(self.parse_or())
                    if self.peek()[0] == "comma":
                        self.eat()
                self.eat("rpar")
                return ("call", val, args)
            return ("field", val)
        raise ValueError(f"unexpected token {kind}:{val}")


def _lookup(metadata: Any, path: str) -> Any:
    cur = metadata
    for part in path.split("."):
        if cur is None:
            return None
        if hasattr(cur, "value"):  # pw.Json wrapper
            cur = cur.value
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
    if hasattr(cur, "value"):
        cur = cur.value
    return cur


def _eval(node, metadata) -> Any:
    op = node[0]
    if op == "lit":
        return node[1]
    if op == "field":
        return _lookup(metadata, node[1])
    if op == "call":
        fn = _FUNCS.get(node[1])
        if fn is None:
            raise ValueError(f"unsupported filter function {node[1]!r}")
        return fn(*[_eval(a, metadata) for a in node[2]])
    if op == "eq":
        return _eval(node[1], metadata) == _eval(node[2], metadata)
    if op == "ne":
        return _eval(node[1], metadata) != _eval(node[2], metadata)
    if op == "and":
        return bool(_eval(node[1], metadata)) and bool(_eval(node[2], metadata))
    if op == "or":
        return bool(_eval(node[1], metadata)) or bool(_eval(node[2], metadata))
    if op == "not":
        return not bool(_eval(node[1], metadata))
    raise AssertionError(node)


def compile_filter(expression: str | None) -> Callable[[Any], bool]:
    """Compile a filter string to a metadata → bool predicate. None → accept all."""
    if expression is None:
        return lambda _md: True
    ast = _Parser(_tokenize(expression)).parse_or()
    return lambda md: bool(_eval(ast, md))
