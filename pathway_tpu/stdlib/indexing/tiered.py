"""Tiered KNN backend: bounded hot shard in HBM over a host-resident IVF cold
tier — the refactor that makes the "KNN as HBM einsum" flagship hold when the
corpus no longer fits in device memory (ROADMAP #4; the hot/cold discipline of
serving-scale ANN systems: FAISS-style IVF cold tiers, DiskANN-style
fixed-memory serving).

Layout
------
- **Cold tier (authoritative, host)**: every row lives in an
  :class:`~pathway_tpu.stdlib.indexing.ivf.IvfFlatBackend` (k-means coarse
  quantizer, contiguous CSR lists) plus a raw-vector mirror used for device
  rescoring. Host memory scales with the corpus; HBM does not.
- **Hot tier (bounded, HBM)**: a :class:`~pathway_tpu.ops.knn.BruteForceKnnIndex`
  allocated at ``PATHWAY_INDEX_HOT_ROWS`` and never grown past it — recently
  added rows plus rows the maintenance pass promotes for being frequently hit.

Query path (one tick)
---------------------
hot einsum over the resident shard (exact, HBM) ‖ IVF candidate pruning over
the cold tier (host) → cold candidates rescored on device by
``ops.knn.exact_rescore`` → canonical merge. Hot and cold candidates are
scored by the SAME kernel body (``_search_body``: one dot/norm formula, one
canonical (score desc, key asc) tie-break), so the merged top-k is the list a
single-tier brute-force index over the full corpus would return whenever the
cold tier's candidate generation covers the true top-k (always when the IVF is
untrained or probes every list; at its measured recall otherwise — the
approximation is confined to cold, infrequently-hit rows).

Promotion/demotion is **batched and off the query path**: the engine node
calls :meth:`maintain` after a tick's answers are emitted; rows hit at least
``PATHWAY_INDEX_PROMOTE_HITS`` times promote into free hot slots, least-
recently-hit residents demote to make room (they remain in the cold tier —
demotion only drops the HBM mirror). ``maintain()`` applies at most
``PATHWAY_INDEX_MAINTAIN_BATCH`` moves per pass.

Accounting is exact: ``hits_total`` / ``hot_hits`` count every emitted result
row by serving tier (``pathway_index_hot_hit_ratio``), and promotions/
demotions are monotonic counters. Hot HBM bytes report as
``pathway_device_bytes{component="knn_hot"}``, the host-resident cold tier as
``component="knn_cold"``.

Persistence note: the index node's delta-log snapshot records the add/remove
op sequence, which fully determines VectorBackend/IVF/BM25 state — their
restore is byte-for-byte. Tiered hot membership is additionally QUERY-driven
(hit counters feed promotion), which the log does not replay: a restore gets
the hot set as of the last compacted base plus add-time residency for
replayed rows, and re-warms promotions from live traffic. Answers are
unaffected wherever the cold tier's candidate recall covers the true top-k
(always in its exact regime); at lower recall a just-promoted row can sit one
recall class lower until it re-earns promotion.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any

import numpy as np

from pathway_tpu.internals.keys import tie_order
from pathway_tpu.stdlib.indexing._engine import IndexBackend
from pathway_tpu.stdlib.indexing.ivf import IvfFlatBackend


def _true(_md: Any) -> bool:
    return True


#: live tiered backends (weak — no lifetime coupling), for /metrics + /status
_live_tiered: "weakref.WeakSet[TieredKnnBackend]" = weakref.WeakSet()
_registry_lock = threading.Lock()

#: process-cumulative counters (DeviceStats discipline): the Prometheus
#: counter families must stay monotonic, which a sum over live weakly-held
#: backends is not — a rebuilt pipeline dropping an old backend would read as
#: a counter reset and extrapolate phantom rate spikes
_counters = {
    "hits_total": 0,
    "hot_hits": 0,
    "promotions_total": 0,
    "demotions_total": 0,
}


def _count(name: str, n: int) -> None:
    if n:
        with _registry_lock:
            _counters[name] += n


class TieredKnnBackend(IndexBackend):
    """Bounded-HBM hot shard + host IVF cold tier with async promotion."""

    #: per-shard top-k partials merge exactly (scores are content-based and
    #: shard-independent, like the brute-force backend's)
    shardable = True

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        hot_rows: int | None = None,
        nlist: int | None = None,
        nprobe: int | None = None,
        min_train: int = 4096,
        promote_hits: int | None = None,
        maintain_batch: int | None = None,
        seed: int = 0,
    ):
        from pathway_tpu.internals.config import get_pathway_config
        from pathway_tpu.ops.knn import BruteForceKnnIndex, _pad_to_capacity

        cfg = get_pathway_config()
        if metric not in ("cos", "dot", "l2sq"):
            raise ValueError(f"unsupported metric {metric!r}")
        self.dimension = dimension
        self.metric = metric
        self.hot_rows = hot_rows if hot_rows is not None else cfg.index_hot_rows
        if self.hot_rows < 1:
            raise ValueError(f"hot_rows must be >= 1, got {self.hot_rows}")
        self.promote_hits = (
            promote_hits if promote_hits is not None else cfg.index_promote_hits
        )
        self.maintain_batch = (
            maintain_batch if maintain_batch is not None else cfg.index_maintain_batch
        )
        # hot shard: capacity FIXED at the bound's power-of-two pad; occupancy
        # never exceeds hot_rows, so _grow() never fires and HBM stays flat
        self.hot = BruteForceKnnIndex(
            dimension=dimension,
            metric=metric,
            capacity=_pad_to_capacity(self.hot_rows),
            component="knn_hot",
        )
        self.cold = IvfFlatBackend(
            dimension=dimension,
            metric=metric,
            nlist=nlist,
            nprobe=nprobe,
            min_train=min_train,
            seed=seed,
        )
        # the tiered search already over-fetches via its ks (and filters at
        # the merge, never inside the cold tier) — the IVF's own post-filter
        # margin on top would compound to ~100x k of per-list selection work
        self.cold.post_filter_mult = 1
        # raw (un-normalized) vector mirror for device rescoring of cold
        # candidates — the IVF stores normalized copies under the cos metric
        cap = 1024
        self._raw = np.zeros((cap, dimension), dtype=np.float32)
        self._raw_slot: dict[int, int] = {}
        self._raw_free: list[int] = []
        self._raw_n = 0
        # hit accounting (monotonic) + per-window promotion bookkeeping
        self.hits_total = 0
        self.hot_hits = 0
        self.promotions_total = 0
        self.demotions_total = 0
        self._cold_hit_counts: dict[int, int] = {}
        self._hot_last_hit: dict[int, int] = {}
        self._clock = 0
        _register(self)

    # ------------------------------------------------------------------ sizing
    def __len__(self) -> int:
        return len(self._raw_slot)

    def cold_bytes(self) -> int:
        """Host-resident bytes of the cold tier (raw mirror + IVF arrays)."""
        b = self._raw.nbytes
        for name in ("_vecs", "_keys", "_live", "_assign"):
            b += getattr(self.cold, name).nbytes
        if self.cold._centroids is not None:
            b += self.cold._centroids.nbytes
        vcsr = getattr(self.cold, "_vecs_csr", None)
        if vcsr is not None:
            b += vcsr.nbytes
        return int(b)

    # ------------------------------------------------------------------ writes
    def _grow_raw(self) -> None:
        cap = len(self._raw) * 2
        new = np.zeros((cap, self.dimension), dtype=np.float32)
        new[: len(self._raw)] = self._raw
        self._raw = new

    def add(self, key: int, item: Any, metadata: Any) -> None:
        vec = np.asarray(item, dtype=np.float32).reshape(-1)
        if vec.shape[0] != self.dimension:
            raise ValueError(
                f"vector dimension {vec.shape[0]} != index dimension {self.dimension}"
            )
        if key in self._raw_slot:
            self.remove(key)
        if self._raw_free:
            slot = self._raw_free.pop()
        else:
            if self._raw_n == len(self._raw):
                self._grow_raw()
            slot = self._raw_n
            self._raw_n += 1
        self._raw[slot] = vec
        self._raw_slot[key] = slot
        self.cold.add(key, vec, metadata)
        if len(self.hot) < self.hot_rows:
            # recently-added rows serve from HBM until demoted
            self.hot.add(key, vec)
            self._hot_last_hit[key] = self._clock

    def remove(self, key: int) -> None:
        # tolerant of unknown keys (a corrupted retraction must poison at most
        # its own row, never the dataflow — the audit plane flags it)
        slot = self._raw_slot.pop(key, None)
        if slot is None:
            return
        self._raw_free.append(slot)
        self.cold.remove(key)
        if key in self.hot._key_to_slot:
            self.hot.remove(key)
            self._hot_last_hit.pop(key, None)
        self._cold_hit_counts.pop(key, None)

    # ------------------------------------------------------------------ search
    def search(self, items, ks, filters):
        from pathway_tpu.ops.knn import _decode_hits, exact_rescore

        if not items:
            return []
        n_live = len(self._raw_slot)
        if n_live == 0:
            return [[] for _ in items]
        kmax = max(ks, default=0)
        if kmax == 0:
            return [[] for _ in items]
        # shared over-fetch heuristic (power-of-two quantized: k is a STATIC
        # jit argument — an occupancy-dependent fetch would recompile the
        # search kernels on nearly every churn tick)
        from pathway_tpu.stdlib.indexing._engine import overfetch

        fetch = overfetch(kmax, n_live)
        qs = np.stack([np.asarray(q, dtype=np.float32) for q in items])
        self._clock += 1
        hot_keys = self.hot._key_to_slot
        # hot tier: exact einsum over the HBM-resident shard (search_device
        # clamps k to the FIXED hot capacity — the compile cache stays closed)
        hot_lists: list[list] = [[] for _ in items]
        if len(self.hot) > 0:
            scores, ids = self.hot.search_device(qs, fetch)
            s_np, i_np = self.hot._fetch_hits(scores, ids)
            hot_lists = _decode_hits(s_np, i_np, self.hot._slot_to_key, fetch)
        # cold tier: IVF prunes to candidate KEYS (host); hot residents are
        # excluded (already exactly scored above) and the union is rescored on
        # device by the same kernel body — scoring the union for every query
        # is sound because every candidate is a real corpus row. Skipped
        # entirely while every live row is hot-resident (small corpora / the
        # warm-up phase of big ones): the host scan would only produce
        # candidates the dedup discards
        cand: list[int] = []
        if len(self.hot) < n_live:
            cold_raw = self.cold.search(
                list(qs), [fetch] * len(items), [_true] * len(items)
            )
            seen: set[int] = set()
            for hits in cold_raw:
                for key, _s in hits:
                    if key in hot_keys or key in seen:
                        continue
                    seen.add(key)
                    cand.append(key)
        cold_lists: list[list] = [[] for _ in items]
        if cand:
            mat = self._raw[[self._raw_slot[c] for c in cand]]
            # k = fetch, NOT min(fetch, len(cand)): exact_rescore clamps k to
            # the padded power-of-two capacity, so the static (cap, k) pair
            # stays a small closed set instead of recompiling per tick
            cold_lists = exact_rescore(mat, cand, qs, fetch, self.metric)
        # canonical merge + post-filter + exact per-tier hit accounting
        meta = self.cold.metadata
        out = []
        for qi, (k, flt) in enumerate(zip(ks, filters)):
            merged = list(hot_lists[qi]) + list(cold_lists[qi])
            merged.sort(key=lambda kv: (-kv[1], tie_order(kv[0])))
            picked: list[tuple[int, float]] = []
            hot_n = 0
            for key, score in merged:
                if len(picked) >= k:
                    break
                if flt(meta.get(key)):
                    picked.append((key, float(score)))
                    if key in hot_keys:
                        hot_n += 1
                        self._hot_last_hit[key] = self._clock
                    else:
                        self._cold_hit_counts[key] = (
                            self._cold_hit_counts.get(key, 0) + 1
                        )
            self.hits_total += len(picked)
            self.hot_hits += hot_n
            _count("hits_total", len(picked))
            _count("hot_hits", hot_n)
            out.append(picked)
        return out

    # -------------------------------------------------------------- maintenance
    def maintain(self) -> None:
        """Batched promotion/demotion between ticks (called by the engine node
        AFTER a tick's answers are emitted — never on the query path)."""
        hot_keys = self.hot._key_to_slot
        cand = [
            (c, k)
            for k, c in self._cold_hit_counts.items()
            if c >= self.promote_hits and k in self._raw_slot and k not in hot_keys
        ]
        if cand:
            cand.sort(key=lambda ck: (-ck[0], tie_order(ck[1])))
            cand = cand[: self.maintain_batch]
            room = self.hot_rows - len(self.hot)
            need = len(cand) - room
            if need > 0:
                # demote least-recently-hit residents to make room — but never
                # a row that served a hit this very window
                lru = sorted(
                    hot_keys,
                    key=lambda k: (self._hot_last_hit.get(k, -1), tie_order(k)),
                )
                demote = [
                    k for k in lru if self._hot_last_hit.get(k, -1) < self._clock
                ][:need]
                for k in demote:
                    self.hot.remove(k)
                    self._hot_last_hit.pop(k, None)
                self.demotions_total += len(demote)
                _count("demotions_total", len(demote))
                room = self.hot_rows - len(self.hot)
            promote = cand[:room]
            if promote:
                keys = [k for _c, k in promote]
                rows = self._raw[[self._raw_slot[k] for k in keys]]
                self.hot.add_batch(keys, rows)
                for k in keys:
                    self._hot_last_hit[k] = self._clock
                self.promotions_total += len(promote)
                _count("promotions_total", len(promote))
        # the window ENDS here: counts reset every maintenance pass, so
        # promote_hits means "hits within one window" (per the knob's
        # contract) — lifetime accumulation would eventually promote every
        # occasionally-hit row and churn the hot shard forever
        self._cold_hit_counts.clear()

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict[str, Any]:
        hot_n = len(self.hot)
        return {
            "hot_rows": hot_n,
            "hot_bound": self.hot_rows,
            "cold_rows": len(self._raw_slot) - hot_n,
            "hits_total": self.hits_total,
            "hot_hits": self.hot_hits,
            "hot_hit_ratio": (
                round(self.hot_hits / self.hits_total, 6) if self.hits_total else None
            ),
            "promotions_total": self.promotions_total,
            "demotions_total": self.demotions_total,
            "hot_device_bytes": self.hot.device_bytes(),
            "cold_host_bytes": self.cold_bytes(),
        }

    # ------------------------------------------------------------------ pickle
    def __setstate__(self, d):
        # weak registrations (tier registry + knn_cold memory) don't survive
        # pickling; the hot index re-registers knn_hot in its own __setstate__
        self.__dict__.update(d)
        _register(self)


def _register(backend: TieredKnnBackend) -> None:
    from pathway_tpu.observability import device as _dev_prof

    with _registry_lock:
        _live_tiered.add(backend)
    _dev_prof.register_memory(backend, "knn_cold", lambda t: t.cold_bytes())


def tier_stats() -> dict[str, Any] | None:
    """Tiered-index telemetry, or None when no backend lives — feeds
    ``pathway_index_*`` on /metrics and the ``index`` block on /status.
    Residency/bytes gauges sum over LIVE backends; hit/promotion/demotion
    counters are process-cumulative (monotonic even when a rebuilt pipeline
    drops an old backend — Prometheus counter semantics)."""
    with _registry_lock:
        insts = list(_live_tiered)
        counters = dict(_counters)
    if not insts:
        return None
    agg = {
        "backends": len(insts),
        "hot_rows": 0,
        "hot_bound": 0,
        "cold_rows": 0,
        "hot_device_bytes": 0,
        "cold_host_bytes": 0,
    }
    for b in insts:
        s = b.stats()
        for k in agg:
            if k != "backends":
                agg[k] += s[k]
    agg.update(counters)
    agg["hot_hit_ratio"] = (
        round(agg["hot_hits"] / agg["hits_total"], 6) if agg["hits_total"] else None
    )
    return agg
