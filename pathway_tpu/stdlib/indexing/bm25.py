"""Full-text BM25 index (reference ``stdlib/indexing/bm25.py:41`` TantivyBM25).

The reference wraps the tantivy crate; full-text scoring is memory-bound, not
FLOP-bound, so here it is a host-side inverted index (``_engine.BM25Backend``)
with standard Okapi BM25 scoring. The class keeps the reference's name for
drop-in compatibility.
"""

from __future__ import annotations

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing._engine import BM25Backend
from pathway_tpu.stdlib.indexing.data_index import InnerIndex


class TantivyBM25(InnerIndex):
    def __init__(
        self,
        data_column: ColumnReference,
        *,
        metadata_column: ColumnExpression | None = None,
        ram_budget: int | None = None,  # accepted for API parity; unused
        in_memory_index: bool = True,
    ):
        super().__init__(
            data_column,
            metadata_column=metadata_column,
            backend_factory=BM25Backend,
        )


BM25 = TantivyBM25
