"""KNN inner indexes (reference ``stdlib/indexing/nearest_neighbors.py:65-262``).

``BruteForceKnn`` is the TPU-native flagship: the ``[N, d]`` matrix lives in device
HBM, search is a jitted einsum + top_k (``pathway_tpu/ops/knn.py``). ``LshKnn``
keeps the reference API over the LSH backend; ``UsearchKnn`` — the reference's
ANN index name — routes to :class:`IvfFlatKnn` (k-means coarse quantizer +
exact in-list scoring) so asking for an approximate index delivers sub-linear
ANN costs rather than silently aliasing the exact scan (VERDICT r5 #7).
"""

from __future__ import annotations

import enum
from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.indexing._engine import VectorBackend
from pathway_tpu.stdlib.indexing.data_index import InnerIndex


class DistanceMetric(enum.Enum):
    COS = "cos"
    L2SQ = "l2sq"
    DOT = "dot"


def _embedder_transform(embedder):
    """Wrap a text column into vectors via the embedder UDF (batched at the UDF
    layer — ops/microbatch.py — not per row like the reference)."""
    if embedder is None:
        return None

    def transform(table, expr):
        return embedder(expr)

    return transform


class BruteForceKnn(InnerIndex):
    def __init__(
        self,
        data_column: ColumnReference,
        dimensions: int,
        *,
        reserved_space: int = 1024,
        metric: DistanceMetric | str = DistanceMetric.COS,
        metadata_column: ColumnExpression | None = None,
        embedder=None,
    ):
        metric_val = metric.value if isinstance(metric, DistanceMetric) else str(metric)
        transform = _embedder_transform(embedder)
        super().__init__(
            data_column,
            metadata_column=metadata_column,
            backend_factory=lambda: VectorBackend(
                dimension=dimensions, metric=metric_val, reserved_space=reserved_space
            ),
            item_transform=transform,
        )
        self.dimensions = dimensions
        self.metric = metric_val


class LshKnn(InnerIndex):
    """Approximate KNN: LSH band buckets prune candidates, exact scoring ranks
    them (reference ``LshKnn``; backend in ``_engine.LshVectorBackend``)."""

    def __init__(
        self,
        data_column: ColumnReference,
        dimensions: int,
        *,
        reserved_space: int = 1024,
        metric: DistanceMetric | str = DistanceMetric.COS,
        metadata_column: ColumnExpression | None = None,
        embedder=None,
        n_or: int = 10,
        n_and: int = 8,
        bucket_length: float = 1.0,
    ):
        from pathway_tpu.stdlib.indexing._engine import LshVectorBackend

        metric_val = metric.value if isinstance(metric, DistanceMetric) else str(metric)
        transform = _embedder_transform(embedder)
        super().__init__(
            data_column,
            metadata_column=metadata_column,
            backend_factory=lambda: LshVectorBackend(
                dimension=dimensions,
                metric=metric_val,
                n_or=n_or,
                n_and=n_and,
                bucket_length=bucket_length,
            ),
            item_transform=transform,
        )


class IvfFlatKnn(InnerIndex):
    """IVF-flat approximate KNN (the HNSW-class retriever; backend in
    ``indexing/ivf.py``): k-means coarse quantizer + exact scoring inside the
    ``nprobe`` nearest lists. Sub-linear search for big corpora with measured
    recall@10 ≥ 0.95 vs brute force (``tests/test_ivf.py``)."""

    def __init__(
        self,
        data_column: ColumnReference,
        dimensions: int,
        *,
        metric: DistanceMetric | str = DistanceMetric.COS,
        metadata_column: ColumnExpression | None = None,
        embedder=None,
        nlist: int | None = None,
        nprobe: int | None = None,
        min_train: int = 4096,
    ):
        from pathway_tpu.stdlib.indexing.ivf import IvfFlatBackend

        metric_val = metric.value if isinstance(metric, DistanceMetric) else str(metric)
        transform = _embedder_transform(embedder)
        super().__init__(
            data_column,
            metadata_column=metadata_column,
            backend_factory=lambda: IvfFlatBackend(
                dimension=dimensions,
                metric=metric_val,
                nlist=nlist,
                nprobe=nprobe,
                min_train=min_train,
            ),
            item_transform=transform,
        )
        self.dimensions = dimensions
        self.metric = metric_val


class TieredKnn(InnerIndex):
    """Tiered KNN (``indexing/tiered.py``): a bounded hot shard in HBM
    (recently added + frequently hit rows, ``PATHWAY_INDEX_HOT_ROWS``) over a
    host-resident IVF cold tier, with batched promotion/demotion between
    ticks — serves corpora far beyond HBM capacity on a fixed device-memory
    budget."""

    def __init__(
        self,
        data_column: ColumnReference,
        dimensions: int,
        *,
        metric: DistanceMetric | str = DistanceMetric.COS,
        metadata_column: ColumnExpression | None = None,
        embedder=None,
        hot_rows: int | None = None,
        nlist: int | None = None,
        nprobe: int | None = None,
        min_train: int = 4096,
        promote_hits: int | None = None,
    ):
        from pathway_tpu.stdlib.indexing.tiered import TieredKnnBackend

        metric_val = metric.value if isinstance(metric, DistanceMetric) else str(metric)
        transform = _embedder_transform(embedder)
        super().__init__(
            data_column,
            metadata_column=metadata_column,
            backend_factory=lambda: TieredKnnBackend(
                dimension=dimensions,
                metric=metric_val,
                hot_rows=hot_rows,
                nlist=nlist,
                nprobe=nprobe,
                min_train=min_train,
                promote_hits=promote_hits,
            ),
            item_transform=transform,
        )
        self.dimensions = dimensions
        self.metric = metric_val


class UsearchKnn(IvfFlatKnn):
    """Reference API parity for the ANN index name. Routed to :class:`IvfFlatKnn`
    (VERDICT r5 #7): a user asking for the approximate index by the reference
    name gets sub-linear ANN search costs, not a silent exact O(N·d) scan.
    ``reserved_space`` (a usearch capacity hint) is accepted and ignored —
    IVF sizes its lists from the data."""

    def __init__(
        self,
        data_column: ColumnReference,
        dimensions: int,
        *,
        reserved_space: int = 1024,
        metric: DistanceMetric | str = DistanceMetric.COS,
        metadata_column: ColumnExpression | None = None,
        embedder=None,
        **ivf_kwargs,
    ):
        super().__init__(
            data_column,
            dimensions,
            metric=metric,
            metadata_column=metadata_column,
            embedder=embedder,
            **ivf_kwargs,
        )
