"""Indexing stdlib (reference ``python/pathway/stdlib/indexing/``): KNN / BM25 /
hybrid inner indexes, DataIndex payload joins, and retriever factories.

The vector path is the TPU north star: the index matrix lives in device HBM and
search is a jitted einsum + top_k (``pathway_tpu/ops/knn.py``).
"""

from pathway_tpu.stdlib.indexing.bm25 import BM25, TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex, InnerIndex, _SCORE
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    IvfFlatKnn,
    LshKnn,
    TieredKnn,
    UsearchKnn,
)
from pathway_tpu.stdlib.indexing.retrievers import (
    AbstractRetrieverFactory,
    BruteForceKnnFactory,
    HybridIndexFactory,
    IvfFlatKnnFactory,
    LshKnnFactory,
    TantivyBM25Factory,
    TieredKnnFactory,
    UsearchKnnFactory,
)
from pathway_tpu.stdlib.indexing.tiered import TieredKnnBackend, tier_stats

__all__ = [
    "AbstractRetrieverFactory",
    "BM25",
    "BruteForceKnn",
    "BruteForceKnnFactory",
    "DataIndex",
    "DistanceMetric",
    "HybridIndex",
    "HybridIndexFactory",
    "InnerIndex",
    "IvfFlatKnn",
    "IvfFlatKnnFactory",
    "LshKnn",
    "LshKnnFactory",
    "TantivyBM25",
    "TantivyBM25Factory",
    "TieredKnn",
    "TieredKnnBackend",
    "TieredKnnFactory",
    "UsearchKnn",
    "UsearchKnnFactory",
    "tier_stats",
]
