"""InnerIndex / DataIndex — search results joined back to payload columns.

API parity with the reference's ``stdlib/indexing/data_index.py:206,278``: an
``InnerIndex`` answers queries with lists of (doc id, score); ``DataIndex`` joins
those ids back to the data table's columns, either collapsed (one row per query,
tuple-valued columns, score-descending order) or flat (one row per match).

The inner index runs as an engine :class:`ExternalIndexNode`; queries through
``query_as_of_now`` are answered against index state at arrival and never revised
(the serving discipline, SURVEY §3.3), while ``query`` keeps answers consistent
under doc updates (one batched re-search per doc tick — a single einsum on TPU).
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing._engine import ExternalIndexNode, IndexBackend, MergeIndexRepliesNode

_SCORE = "_pw_index_reply_score"
_INDEX_REPLY = "_pw_index_reply"


class InnerIndex:
    """Engine-backed index over ``data_column`` of ``data_table``."""

    def __init__(
        self,
        data_column: ColumnReference,
        metadata_column: ColumnExpression | None = None,
        backend_factory: Callable[[], IndexBackend] | None = None,
        item_transform: Callable[[Table, Any], ColumnExpression] | None = None,
    ):
        self.data_column = data_column
        self.data_table: Table = data_column.table
        self.metadata_column = metadata_column
        self.backend_factory = backend_factory
        # maps a (table, raw item expr) to what the backend indexes/searches
        # (e.g. embedder application for vector indexes)
        self.item_transform = item_transform or (lambda _table, e: e)

    def _docs_table(self, with_payload: bool = False) -> Table:
        table = self.data_table
        item = self.item_transform(table, self.data_column)
        meta = self.metadata_column if self.metadata_column is not None else None
        cols = {"__item": item}
        cols["__meta"] = meta if meta is not None else 0
        if with_payload:
            # replica-served retrieval: the raw document text rides the doc
            # rows so the changelog feed can cast it (the __item column is the
            # embedded vector — not enough to rebuild the response payload)
            cols["__payload"] = self.data_column
        return table.select(**cols)

    def _raw_reply(
        self,
        query_column: ColumnReference,
        number_of_matches: Any,
        metadata_filter: Any,
        as_of_now: bool,
    ) -> Table:
        qtable: Table = query_column.table
        qitem = self.item_transform(qtable, query_column)
        cols = {"__item": qitem}
        cols["__k"] = number_of_matches if number_of_matches is not None else 3
        cols["__filter"] = metadata_filter if metadata_filter is not None else None
        queries = qtable.select(**cols)
        cap = None
        if as_of_now:
            from pathway_tpu.fabric import index_replica as _index_replica

            cap = _index_replica.current_capture()
            if cap is not None:
                cap.bind(self)
                if cap.composite:
                    # hybrid/composite retrieval: one replica can't reproduce
                    # the composition — leave the nodes uncaptured (the route
                    # always forwards)
                    cap = None
        docs = self._docs_table(with_payload=cap is not None)
        factory = self.backend_factory
        if cap is None:
            make = lambda: ExternalIndexNode(factory, as_of_now=as_of_now)  # noqa: E731
        else:

            def make(_cap=cap, _factory=factory, _aon=as_of_now):
                node = ExternalIndexNode(_factory, as_of_now=_aon)
                # every worker of every process builds its own instance;
                # each one feeds the same route (disjoint doc shards)
                _cap.attach_node(node)
                return node

        node = LogicalNode(
            make,
            [docs._node, queries._node],
            name="external_index",
        )
        merge = LogicalNode(
            lambda: MergeIndexRepliesNode(), [node], name="index_merge"
        )
        schema = schema_mod.schema_from_dtypes({_INDEX_REPLY: dt.ANY})
        return Table(merge, schema, qtable._universe.subset())

    def query(self, query_column, *, number_of_matches=3, metadata_filter=None):
        return self._raw_reply(query_column, number_of_matches, metadata_filter, False)

    def query_as_of_now(self, query_column, *, number_of_matches=3, metadata_filter=None):
        return self._raw_reply(query_column, number_of_matches, metadata_filter, True)


class _DataIndexResult:
    """select()-able view over (query table × collapsed/flat matches); resolves
    ``pw.left`` to the query table's columns and ``pw.right`` to the joined data
    columns. In flat mode the query columns are materialized onto the match rows
    (prefixed ``__q_``) since each query yields many match rows."""

    def __init__(
        self,
        query_table: Table,
        right: Table,
        left_to_right_universe: bool,
        left_prefix: str | None = None,
    ):
        self._query_table = query_table
        self._right = right
        self._same_universe = left_to_right_universe
        self._left_prefix = left_prefix

    def _left_col(self, name: str):
        if self._left_prefix is not None:
            return self._right[f"{self._left_prefix}{name}"]
        return self._query_table[name]

    def _resolve(self, e):
        from pathway_tpu.internals import expression as expr_mod

        e = expr_mod.wrap(e)
        qcols = set(self._query_table.column_names())

        def walk(x):
            if isinstance(x, ColumnReference) and x.table is None:
                side = getattr(x, "_placeholder_side", "this")
                if side == "left":
                    return self._left_col(x.name)
                if side == "right":
                    return self._right[x.name]
                if x.name in self._right.column_names():
                    return self._right[x.name]
                if x.name in qcols:
                    return self._left_col(x.name)
                raise KeyError(f"column {x.name!r} in neither side of the index result")
            if isinstance(x, ColumnReference) and x.table is self._query_table:
                return self._left_col(x.name)
            args = x._args()
            if not args:
                return x
            return x._with_args(tuple(walk(a) for a in args))

        return walk(e)

    def select(self, *args, **kwargs) -> Table:
        from pathway_tpu.internals import expression as expr_mod

        exprs = {}
        for a in args:
            bound = self._resolve(a)
            name = expr_mod.smart_name(bound)
            if name is None:
                raise ValueError("positional select args must be column references")
            exprs[name] = bound
        for n, e in kwargs.items():
            exprs[n] = self._resolve(e)
        base = self._right.with_universe_of(self._query_table) if self._same_universe else self._right
        # expressions may span query table + right table (same universe)
        return base.select(**exprs)


class DataIndex:
    """Reference ``DataIndex``: inner index + payload join."""

    def __init__(self, data_table: Table, inner_index: InnerIndex):
        self.data_table = data_table
        self.inner_index = inner_index

    def _query_impl(
        self,
        query_column: ColumnReference,
        number_of_matches,
        collapse_rows: bool,
        metadata_filter,
        as_of_now: bool,
    ):
        import pathway_tpu as pw

        qtable: Table = query_column.table
        raw = (
            self.inner_index.query_as_of_now(
                query_column,
                number_of_matches=number_of_matches,
                metadata_filter=metadata_filter,
            )
            if as_of_now
            else self.inner_index.query(
                query_column,
                number_of_matches=number_of_matches,
                metadata_filter=metadata_filter,
            )
        )
        # flatten replies → one row per (query, match)
        rep = raw.select(reply=raw[_INDEX_REPLY], __qid=raw.id)
        flat = rep.flatten(rep.reply)
        flat = flat.select(
            __qid=flat["__qid"],
            __doc=pw.this.reply.get(0),
            __score=pw.apply_with_type(lambda r: float(r[1]), dt.FLOAT, pw.this.reply),
        )
        data_cols = self.data_table.column_names()
        ixed = self.data_table.ix(flat["__doc"], optional=True)  # one shared join
        matched = flat.with_columns(**{n: ixed[n] for n in data_cols})
        if not collapse_rows:
            # flat mode: one row per match; pull query columns onto the match rows
            q_ixed = qtable.ix(matched["__qid"])
            with_q = matched.with_columns(
                **{f"__q_{n}": q_ixed[n] for n in qtable.column_names()},
                **{_SCORE: matched["__score"]},
            )
            return _DataIndexResult(
                qtable, with_q, left_to_right_universe=False, left_prefix="__q_"
            )
        # collapse: per query, score-ordered tuples of each data column
        grouped = matched.groupby(
            matched["__qid"], id=matched["__qid"], sort_by=-matched["__score"]
        )
        agg = {n: pw.reducers.tuple(matched[n]) for n in data_cols}
        agg[_SCORE] = pw.reducers.tuple(matched["__score"])
        collapsed = grouped.reduce(**agg)
        # queries with zero matches have no group — pad with None (reference:
        # left join; DocumentStore coalesces to ())
        out_cols = data_cols + [_SCORE]
        if as_of_now:
            # serving discipline: an as-of-now query emits NOTHING until the
            # index has answered it, so the pad universe is the ANSWERED
            # replies (``rep`` rows exist exactly then; ``()`` for zero
            # matches). Padding over the full query universe raced the
            # answer: the reply can land ticks after arrival (the query
            # embedding rides the cross-tick microbatch path), and a REST
            # client would be resolved with the provisional padded row.
            base = rep.select(
                **{n: pw.declare_type(dt.ANY, None) for n in out_cols}
            )
        else:
            base = qtable.select(
                **{n: pw.declare_type(dt.ANY, None) for n in out_cols}
            )
        padded = base.update_cells(collapsed.promise_universe_is_subset_of(base))
        return _DataIndexResult(qtable, padded, left_to_right_universe=True)

    def query(self, query_column, *, number_of_matches=3, collapse_rows=True, metadata_filter=None):
        return self._query_impl(
            query_column, number_of_matches, collapse_rows, metadata_filter, False
        )

    def query_as_of_now(
        self, query_column, *, number_of_matches=3, collapse_rows=True, metadata_filter=None
    ):
        return self._query_impl(
            query_column, number_of_matches, collapse_rows, metadata_filter, True
        )
