"""External-index engine node + index backends.

Counterpart of the reference's ``use_external_index_as_of_now`` machinery
(``src/engine/dataflow/operators/external_index.rs:81`` + ``external_integration/``):
the index lives OUTSIDE the incremental state, updated by the doc stream's
additions/retractions and queried per query row. Two query disciplines:

- **as-of-now** (reference behavior): each query is answered against the index
  state at its arrival tick and the answer is never revised; query retractions
  retract their answers.
- **consistent** (reference's pure-dataflow LshKnn ``query``): answers are kept
  up to date — when docs change, all live queries are re-answered and deltas
  emitted. On TPU this is the natural mode for the brute-force index: re-answering
  every query is ONE batched einsum (``ops/knn.py``), not a per-query loop.

Backends: ``VectorBackend`` (ops.knn HBM index), ``BM25Backend`` (host-side
inverted index — memory-bound, not FLOP-bound, so it stays on host like the
reference's tantivy).
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals.keys import tie_order, tie_order_u64
from pathway_tpu.stdlib.indexing._filters import compile_filter


class IndexBackend:
    """add/remove/search over (key, item, metadata) triples."""

    def add(self, key: int, item: Any, metadata: Any) -> None:
        raise NotImplementedError

    def remove(self, key: int) -> None:
        raise NotImplementedError

    def search(
        self, items: list[Any], ks: list[int], filters: list[Callable[[Any], bool]]
    ) -> list[list[tuple[int, float]]]:
        """Per query: top-k (doc_key, score) pairs, best (highest score) first."""
        raise NotImplementedError


def overfetch(kmax: int, n_live: int) -> int:
    """Candidate over-fetch so post-filtering still fills k (filters are rare
    and the einsum cost is independent of k). Rounded up to a power of two:
    ``k`` is a static jit argument of the search kernels, so an unquantized
    fetch would compile a fresh kernel every time the live-row count moves.
    Shared by VectorBackend and the tiered backend — one factor to tune."""
    fetch = min(n_live, max(kmax * 10, kmax))
    return 1 << max(0, (fetch - 1)).bit_length() if fetch else 0


class VectorBackend(IndexBackend):
    """Dense KNN over the HBM-resident brute-force index (ops/knn.py)."""

    #: KNN scores are shard-independent: per-shard top-k partials merge exactly
    shardable = True

    def __init__(self, dimension: int, metric: str = "cos", reserved_space: int = 1024):
        from pathway_tpu.ops.knn import BruteForceKnnIndex

        self.index = BruteForceKnnIndex(
            dimension=dimension, metric=metric, capacity=max(reserved_space, 128)
        )
        self.metadata: dict[int, Any] = {}

    def add(self, key, item, metadata):
        self.index.add(key, np.asarray(item, dtype=np.float32))
        self.metadata[key] = metadata

    def remove(self, key):
        self.index.remove(key)
        self.metadata.pop(key, None)

    def search(self, items, ks, filters):
        if not items:
            return []
        n_live = len(self.index)
        if n_live == 0:
            return [[] for _ in items]
        kmax = max(ks, default=0)
        fetch = overfetch(kmax, n_live)
        batch = np.stack([np.asarray(q, dtype=np.float32) for q in items])
        raw = self.index.search(batch, fetch)
        out = []
        for hits, k, flt in zip(raw, ks, filters):
            picked = []
            for key, score in hits:
                if len(picked) >= k:
                    break
                if flt(self.metadata.get(key)):
                    picked.append((key, float(score)))
            out.append(picked)
        return out


class BM25Backend(IndexBackend):
    """Okapi BM25 over a host-side inverted index (k1=1.2, b=0.75)."""

    K1 = 1.2
    B = 0.75

    #: BM25 idf depends on GLOBAL corpus statistics; per-shard scores would
    #: change results, so this backend stays on one worker
    shardable = False

    def __init__(self):
        self.docs: dict[int, dict[str, int]] = {}
        self.metadata: dict[int, Any] = {}
        self.doc_len: dict[int, int] = {}
        self.postings: dict[str, dict[int, int]] = defaultdict(dict)
        self.total_len = 0

    @staticmethod
    def _tokens(text: str) -> list[str]:
        import re

        return re.findall(r"[a-z0-9]+", str(text).lower())

    def add(self, key, item, metadata):
        toks = self._tokens(item)
        tf: dict[str, int] = defaultdict(int)
        for t in toks:
            tf[t] += 1
        self.docs[key] = dict(tf)
        self.metadata[key] = metadata
        self.doc_len[key] = len(toks)
        self.total_len += len(toks)
        for t, c in tf.items():
            self.postings[t][key] = c

    def remove(self, key):
        tf = self.docs.pop(key, None)
        if tf is None:
            return
        self.metadata.pop(key, None)
        self.total_len -= self.doc_len.pop(key, 0)
        for t in tf:
            self.postings[t].pop(key, None)
            if not self.postings[t]:
                del self.postings[t]

    def search(self, items, ks, filters):
        n = len(self.docs)
        out = []
        avgdl = (self.total_len / n) if n else 1.0
        for query, k, flt in zip(items, ks, filters):
            scores: dict[int, float] = defaultdict(float)
            for t in self._tokens(query):
                posting = self.postings.get(t)
                if not posting:
                    continue
                idf = math.log(1 + (n - len(posting) + 0.5) / (len(posting) + 0.5))
                for key, tf in posting.items():
                    dl = self.doc_len[key] or 1
                    scores[key] += (
                        idf
                        * tf
                        * (self.K1 + 1)
                        / (tf + self.K1 * (1 - self.B + self.B * dl / avgdl))
                    )
            ranked = sorted(scores.items(), key=lambda kv: (-kv[1], tie_order(kv[0])))
            picked = [
                (key, float(s)) for key, s in ranked if flt(self.metadata.get(key))
            ][:k]
            out.append(picked)
        return out


class ExternalIndexNode(Node):
    """input0 = docs (item, metadata); input1 = queries (item, k, filter).

    With a shardable backend (KNN), docs shard by key across workers and
    queries BROADCAST to every shard: each instance answers over its local
    shard and emits a PARTIAL reply row (killing the r2 worker-0 serialization
    of the FLOP-heavy index — reference ``operators/external_index.rs:81`` runs
    on one worker; this fans out). ``MergeIndexRepliesNode`` downstream merges
    partials into the final per-query reply. Non-shardable backends (BM25:
    global idf) keep the SOLO placement; their single partial passes through
    the same merge.
    """

    name = "external_index"

    # _filter_cache (compiled callables) is rebuilt lazily, not persisted.
    # The backend payload is NOT in snapshot_attrs: query bookkeeping
    # (_live_queries/_emitted/_tok) snapshots as small positional state while
    # the backend persists through the incremental chunk-store protocol below
    # (delta log + periodic compacted base) — re-pickling a 1M×384 HBM index
    # every snapshot tick is ~1.5 GB/interval (VERDICT "What's weak" #4).
    snapshot_attrs = ("_live_queries", "_emitted", "_tok")

    #: opt into the persistence layer's generation-independent SnapshotStore
    #: (persistence/snapshots.py): snapshot_state_store / restore_state_store
    #: are called with a per-(worker, node) chunk store
    uses_snapshot_store = True

    def exchange_key(self, port):
        from pathway_tpu.engine.graph import BROADCAST, SOLO

        if not getattr(self.backend, "shardable", False):
            return SOLO
        if port == 0:
            return lambda batch: batch.keys  # docs shard by key
        return BROADCAST  # queries fan out to every doc shard

    def __init__(self, backend_factory: Callable[[], IndexBackend], as_of_now: bool):
        super().__init__(n_inputs=2)
        self.backend = backend_factory()
        self.as_of_now = as_of_now
        self._live_queries: dict[int, tuple[Any, int, str | None]] = {}
        self._emitted: dict[int, tuple] = {}  # query key -> partial tuple emitted
        self._filter_cache: dict[str | None, Callable] = {}
        # identifies THIS shard's partials in the merge state (stable within a
        # run; snapshotted so operator persistence keeps partials addressable)
        import os as _os

        self._tok = int.from_bytes(_os.urandom(8), "little")
        # replica-served retrieval (fabric/index_replica): when a retrieval
        # route is armed, this worker's backend mutations — extended with the
        # raw payload text riding the docs' ``__payload`` column — feed the
        # route's IndexRoute so every door can replay them into a local
        # replica index; None costs nothing
        self.replica_feed: Any = None
        # -- incremental snapshot state (persistence plane) -------------------
        # flipped on by Persistence.on_graph_built under operator persistence;
        # off by default so non-persisted runs never grow an op log
        self.snapshot_log_enabled = False
        self._delta_log: list[tuple] = []  # ("a", key, item, meta) | ("r", key)
        self._snap_base: str | None = None  # current compacted-base chunk name
        self._snap_deltas: list[str] = []  # delta chunk names since the base
        self._snap_base_bytes = 0
        self._snap_delta_bytes = 0
        self._snap_seq = 0
        # backend mutations applied / covered by persisted chunks: lets a
        # log-less (snapshot-at-close) save skip the base rewrite when nothing
        # changed since the last one
        self._snap_mutations = 0
        self._snap_covered = 0

    # -- operator snapshots (O(delta) discipline) ----------------------------
    def snapshot_state(self):
        """Store-less fallback (direct callers / non-store persistence paths):
        small positional state plus the whole backend, the pre-r13 shape."""
        state = {a: getattr(self, a) for a in self.snapshot_attrs}
        state["backend_whole"] = self.backend
        return state

    def restore_state(self, state):
        state = dict(state)
        backend = state.pop("backend_whole", None)
        if backend is not None:
            self.backend = backend
        for a, v in state.items():
            setattr(self, a, v)
        if self.replica_feed is not None:
            # restored backends never re-ran process(), so the replica feed's
            # changelog slice was not re-derived — peers must not trust a
            # snapshot RPC from this process (they forward until fresh ops)
            self.replica_feed.note_restored()

    def snapshot_state_store(self, store):
        """Incremental snapshot: persist only the mutation delta log since the
        last snapshot tick; write a fresh compacted base only when the
        accumulated deltas exceed ``PATHWAY_INDEX_COMPACT_FRAC`` of the base
        bytes (or none exists yet). The generation entry carries just the
        chunk manifest + query bookkeeping — restore loads the base and
        replays the deltas in order."""
        import pickle as _pickle

        from pathway_tpu.internals.config import get_pathway_config

        state = {a: getattr(self, a) for a in self.snapshot_attrs}
        cfg = get_pathway_config()
        if cfg.index_snapshot == "whole":
            state["backend_whole"] = self.backend
            self._delta_log = []
            # drop the chunk-chain bookkeeping: the store's referenced set is
            # empty this tick, so post-commit GC deletes the aux chunks — a
            # later delta-mode snapshot must start a fresh base, not commit a
            # manifest naming deleted chunks
            self._snap_base = None
            self._snap_deltas = []
            self._snap_base_bytes = 0
            self._snap_delta_bytes = 0
            return state
        payload = _pickle.dumps(self._delta_log) if self._delta_log else None
        new_bytes = len(payload) if payload is not None else 0
        need_base = (
            self._snap_base is None
            # no live delta log (snapshot-at-close runs keep it disabled):
            # a base rewrite is the only durable form of unsaved mutations
            or (
                not self.snapshot_log_enabled
                and self._snap_mutations > self._snap_covered
            )
            or (
                self._snap_delta_bytes + new_bytes
                > cfg.index_compact_frac * self._snap_base_bytes
            )
        )
        if need_base:
            base_payload = _pickle.dumps(self.backend)
            name = f"base_{self._snap_seq:08d}"
            self._snap_seq += 1
            store.put_chunk(name, base_payload)
            self._snap_base = name
            self._snap_base_bytes = len(base_payload)
            self._snap_deltas = []
            self._snap_delta_bytes = 0
            self._snap_covered = self._snap_mutations
        elif payload is not None:
            name = f"delta_{self._snap_seq:08d}"
            self._snap_seq += 1
            store.put_chunk(name, payload)
            self._snap_deltas.append(name)
            self._snap_delta_bytes += new_bytes
            self._snap_covered = self._snap_mutations
        self._delta_log = []
        store.reference(self._snap_base)
        for name in self._snap_deltas:
            store.reference(name)
        state["backend_chunks"] = {
            "base": self._snap_base,
            "deltas": list(self._snap_deltas),
            "base_bytes": self._snap_base_bytes,
            "delta_bytes": self._snap_delta_bytes,
            "seq": self._snap_seq,
        }
        return state

    def restore_state_store(self, state, store):
        import pickle as _pickle

        state = dict(state)
        chunks = state.pop("backend_chunks", None)
        backend = state.pop("backend_whole", None)
        for a, v in state.items():
            setattr(self, a, v)
        if backend is not None:  # whole-pickle snapshot (escape-hatch mode)
            self.backend = backend
            return
        if chunks is None:
            return  # nothing persisted for the backend (fresh store)
        raw = store.get_chunk(chunks["base"])
        if raw is None:
            raise RuntimeError(
                f"index snapshot base chunk {chunks['base']!r} missing from "
                "persistent storage (was the aux prefix deleted externally?)"
            )
        self.backend = _pickle.loads(raw)
        for name in chunks["deltas"]:
            ops_raw = store.get_chunk(name)
            if ops_raw is None:
                raise RuntimeError(
                    f"index snapshot delta chunk {name!r} missing from "
                    "persistent storage"
                )
            for op in _pickle.loads(ops_raw):
                if op[0] == "a":
                    self.backend.add(op[1], op[2], op[3])
                else:
                    self.backend.remove(op[1])
        # resume the chunk chain where the snapshot left it
        self._snap_base = chunks["base"]
        self._snap_deltas = list(chunks["deltas"])
        self._snap_base_bytes = chunks["base_bytes"]
        self._snap_delta_bytes = chunks["delta_bytes"]
        self._snap_seq = chunks["seq"]
        self._delta_log = []
        if self.replica_feed is not None:
            # see restore_state: a chunk-restored slice is complete in the
            # backend but absent from the replica feed — refuse snapshot RPCs
            self.replica_feed.note_restored()

    def _filter(self, expr):
        if expr not in self._filter_cache:
            try:
                compiled = compile_filter(expr)

                def safe(md, _f=compiled):
                    # evaluation errors (type mismatches against this doc's
                    # metadata) exclude the doc, never kill the dataflow
                    try:
                        return bool(_f(md))
                    except Exception:
                        return False

                self._filter_cache[expr] = safe
            except Exception:
                # a malformed user-supplied filter poisons only its own query
                # (empty reply), never the dataflow — one bad HTTP request must
                # not kill the server
                self._filter_cache[expr] = None
        return self._filter_cache[expr]

    def _answer(self, keys: list[int]) -> list[tuple]:
        qs = [self._live_queries[k] for k in keys]
        filters = [self._filter(q[2]) for q in qs]
        good = [i for i, f in enumerate(filters) if f is not None]
        replies: list[tuple] = [()] * len(qs)  # bad-filter queries reply empty
        if good:
            answered = self.backend.search(
                [qs[i][0] for i in good],
                [qs[i][1] for i in good],
                [filters[i] for i in good],
            )
            for i, r in zip(good, answered):
                replies[i] = tuple(r)
        return replies

    def process(self, inputs, time):
        docs, queries = inputs
        docs_changed = False
        # under operator persistence the exact backend mutation sequence is
        # recorded; the snapshot tick flushes it as one delta chunk (restore =
        # base pickle + in-order replay, so the rebuilt backend is the state
        # the live one had — including slot assignment)
        log = self._delta_log if self.snapshot_log_enabled else None
        feed = self.replica_feed
        fops: list | None = [] if feed is not None else None
        if docs is not None:
            payloads = docs.data.get("__payload") if fops is not None else None
            # removals first: consolidation may reorder a same-key (-1, +1)
            # upsert pair arbitrarily, and remove() is keyed by key alone — an
            # add-then-remove ordering would silently drop the updated doc
            for i in range(len(docs)):
                if docs.diffs[i] < 0:
                    key = int(docs.keys[i])
                    if log is not None:
                        log.append(("r", key))
                    if fops is not None:
                        fops.append(("r", key))
                    self.backend.remove(key)
            for i in range(len(docs)):
                if docs.diffs[i] > 0:
                    key = int(docs.keys[i])
                    item = docs.data["__item"][i]
                    meta = docs.data["__meta"][i]
                    if log is not None:
                        log.append(("a", key, item, meta))
                    if fops is not None:
                        fops.append(
                            (
                                "a",
                                key,
                                item,
                                meta,
                                payloads[i] if payloads is not None else None,
                            )
                        )
                    self.backend.add(key, item, meta)
            docs_changed = len(docs) > 0
            self._snap_mutations += len(docs)
        if fops:
            feed.note_ops(fops)

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []

        def emit(k, reply, query_k, diff):
            out_keys.append(k)
            out_diffs.append(diff)
            out_rows.append((reply, query_k, self._tok))

        new_queries: list[int] = []
        if queries is not None:
            for i in range(len(queries)):  # removals first (see docs loop)
                if queries.diffs[i] < 0:
                    k = int(queries.keys[i])
                    self._live_queries.pop(k, None)
                    old = self._emitted.pop(k, None)
                    if old is not None:
                        emit(k, old[0], old[1], -1)
            for i in range(len(queries)):
                if queries.diffs[i] > 0:
                    k = int(queries.keys[i])
                    self._live_queries[k] = (
                        queries.data["__item"][i],
                        int(queries.data["__k"][i]),
                        queries.data["__filter"][i]
                        if "__filter" in queries.data
                        else None,
                    )
                    new_queries.append(k)

        if self.as_of_now:
            to_answer = new_queries
        else:
            # consistent mode: docs changed → re-answer every live query (one
            # batched search — TPU-friendly), else just the new ones
            to_answer = list(self._live_queries) if docs_changed else new_queries
        if to_answer:
            from pathway_tpu.observability import requests as _requests

            rp = _requests.current()
            if rp is not None and rp.hot:
                import time as _t

                w0 = _t.time_ns()
                replies = self._answer(to_answer)
                rp.note_stage(
                    None, "index/search", w0, _t.time_ns(), len(to_answer)
                )
            else:
                replies = self._answer(to_answer)
            for k, reply in zip(to_answer, replies):
                query_k = self._live_queries[k][1]
                old = self._emitted.get(k)
                if old is not None and old[0] == reply:
                    continue
                if old is not None:
                    emit(k, old[0], old[1], -1)
                emit(k, reply, query_k, +1)
                self._emitted[k] = (reply, query_k)
        if self.as_of_now:
            # answered queries need no further tracking (they are never revised)
            for k in to_answer:
                self._live_queries.pop(k, None)
        # tiered backends rebalance AFTER this tick's answers are emitted:
        # promotion/demotion is batched scatter work that must never sit on
        # the query path (stdlib/indexing/tiered.py)
        maintain = getattr(self.backend, "maintain", None)
        if maintain is not None and (docs_changed or to_answer):
            maintain()
        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(
                out_keys, out_rows, ["__part", "__k", "__tok"], time, diffs=out_diffs
            )
        ]


class MergeIndexRepliesNode(Node):
    """Merges per-shard partial replies into each query's final top-k.

    Keyed (and shard-exchanged) by the QUERY key, so the merge itself scales
    across workers too — no SOLO stage anywhere in the index path. Partials
    accumulate per (query, shard-token); at the frontier every touched query
    re-merges: sort the union by (score desc, doc-key asc), cut to the query's
    k, and emit the delta against the previously-emitted reply.
    """

    name = "index_merge"

    snapshot_attrs = ("state",)

    def __init__(self):
        super().__init__(n_inputs=1)
        # qk -> {"parts": {tok: (partial, k)}, "emitted": tuple | None}
        self.state: dict[int, dict] = {}
        self._touched: set[int] = set()

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None or not len(batch):
            return []
        parts = batch.data["__part"]
        ks = batch.data["__k"]
        toks = batch.data["__tok"]
        for i in range(len(batch)):
            qk = int(batch.keys[i])
            tok = int(toks[i])
            st = self.state.setdefault(qk, {"parts": {}, "emitted": None})
            if batch.diffs[i] > 0:
                st["parts"][tok] = (parts[i], int(ks[i]))
            else:
                st["parts"].pop(tok, None)
            self._touched.add(qk)
        return []

    def on_frontier(self, time):
        if not self._touched:
            return []
        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        for qk in sorted(self._touched):
            st = self.state.get(qk)
            if st is None:
                continue
            if st["parts"]:
                k = max(kk for (_p, kk) in st["parts"].values())
                pairs = [p for (part, _kk) in st["parts"].values() for p in part]
                pairs.sort(key=lambda ds: (-float(ds[1]), tie_order(int(ds[0]))))
                merged: tuple | None = tuple(pairs[:k])
            else:
                merged = None  # every shard retracted: the query is gone
            old = st["emitted"]
            if merged == old:
                if merged is None:
                    # insert+retract within one tick (or all shards retracted
                    # before the first merge): nothing was ever emitted — drop
                    # the entry instead of leaking {'parts': {}, 'emitted': None}
                    del self.state[qk]
                continue
            if old is not None:
                out_keys.append(qk)
                out_diffs.append(-1)
                out_rows.append((old,))
            if merged is not None:
                out_keys.append(qk)
                out_diffs.append(1)
                out_rows.append((merged,))
                st["emitted"] = merged
            else:
                del self.state[qk]
        self._touched = set()
        if not out_keys:
            return []
        return [
            DeltaBatch.from_rows(
                out_keys, out_rows, ["_pw_index_reply"], time, diffs=out_diffs
            )
        ]


class LshVectorBackend(IndexBackend):
    """Approximate KNN via LSH bucket pruning (the ANN answer to the
    reference's usearch/HNSW integrations, ``usearch_integration.rs:20``):
    candidates come from the union of a query's band buckets
    (``stdlib/ml/classifiers/_lsh.py`` bucketers), then score EXACTLY — so
    accuracy degrades only by bucket recall, never by score error, and
    per-shard candidate sets still merge exactly (scores are
    shard-independent)."""

    shardable = True

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        n_or: int = 10,
        n_and: int = 8,
        bucket_length: float = 1.0,
        seed: int = 0,
    ):
        from pathway_tpu.stdlib.ml.classifiers._lsh import (
            generate_cosine_lsh_bucketer,
            generate_euclidean_lsh_bucketer,
        )

        self.metric = metric
        if metric == "dot":
            # hyperplane buckets ignore magnitude, so the true max-inner-product
            # neighbor can be excluded from every bucket; MIPS needs an ALSH
            # transform we don't implement — use the exact brute-force index
            raise ValueError(
                "LshVectorBackend: metric='dot' is not supported (bucket recall "
                "ignores vector magnitude); use BruteForceKnnFactory for "
                "max-inner-product search"
            )
        if metric == "cos":
            self.bucketer = generate_cosine_lsh_bucketer(
                dimension, M=n_and, L=n_or, seed=seed
            )
        elif metric in ("l2sq", "euclidean"):
            self.bucketer = generate_euclidean_lsh_bucketer(
                dimension, M=n_and, L=n_or, A=bucket_length, seed=seed
            )
        else:
            raise ValueError(f"LshVectorBackend: unsupported metric {metric!r}")
        self.vectors: dict[int, np.ndarray] = {}
        self.metadata: dict[int, Any] = {}
        self.bands: dict[int, np.ndarray] = {}  # key -> its L band hashes
        self.buckets: dict[int, set[int]] = {}  # band hash -> keys

    def add(self, key, item, metadata):
        vec = np.asarray(item, dtype=np.float32)
        if key in self.vectors:
            self.remove(key)
        bands = self.bucketer(vec)[0]
        self.vectors[key] = vec
        self.metadata[key] = metadata
        self.bands[key] = bands
        for b in bands.tolist():
            self.buckets.setdefault(int(b), set()).add(key)

    def remove(self, key):
        self.vectors.pop(key, None)
        self.metadata.pop(key, None)
        bands = self.bands.pop(key, None)
        if bands is not None:
            for b in bands.tolist():
                bucket = self.buckets.get(int(b))
                if bucket is not None:
                    bucket.discard(key)
                    if not bucket:
                        del self.buckets[int(b)]

    def _score(self, cand_mat: np.ndarray, q: np.ndarray) -> np.ndarray:
        if self.metric == "cos":
            qn = np.linalg.norm(q) or 1.0
            dn = np.linalg.norm(cand_mat, axis=1)
            dn[dn == 0] = 1.0
            return (cand_mat @ q) / (dn * qn)
        if self.metric in ("l2sq", "euclidean"):
            diff = cand_mat - q[None, :]
            return -(diff * diff).sum(axis=1)
        raise ValueError(f"LshVectorBackend: unsupported metric {self.metric!r}")

    def search(self, items, ks, filters):
        out = []
        for q, k, flt in zip(items, ks, filters):
            qv = np.asarray(q, dtype=np.float32)
            cands: set[int] = set()
            for b in self.bucketer(qv)[0].tolist():
                cands |= self.buckets.get(int(b), set())
            good = [c for c in sorted(cands) if flt(self.metadata.get(c))]
            if not good:
                out.append([])
                continue
            mat = np.stack([self.vectors[c] for c in good])
            scores = self._score(mat, qv)
            order = np.lexsort((tie_order_u64(np.asarray(good, dtype=np.uint64)), -scores))[:k]
            out.append([(good[i], float(scores[i])) for i in order])
        return out
