"""Retriever factories (reference ``stdlib/indexing/retrievers.py`` +
``nearest_neighbors.py:407-565``): deferred index construction so apps (e.g.
DocumentStore) can be configured with *how* to index before the data tables exist.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.internals.table import Table
from pathway_tpu.stdlib.indexing.bm25 import TantivyBM25
from pathway_tpu.stdlib.indexing.data_index import DataIndex
from pathway_tpu.stdlib.indexing.hybrid_index import HybridIndex
from pathway_tpu.stdlib.indexing.nearest_neighbors import (
    BruteForceKnn,
    DistanceMetric,
    IvfFlatKnn,
    LshKnn,
    UsearchKnn,
)


class AbstractRetrieverFactory:
    def build_index(
        self,
        data_column: ColumnReference,
        data_table: Table,
        metadata_column: ColumnExpression | None = None,
    ) -> DataIndex:
        raise NotImplementedError


@dataclass
class BruteForceKnnFactory(AbstractRetrieverFactory):
    dimensions: int | None = None
    reserved_space: int = 1024
    embedder: Any = None
    metric: DistanceMetric | str = DistanceMetric.COS
    _index_cls: type = BruteForceKnn

    def _resolved_dimensions(self) -> int:
        if self.dimensions is not None:
            return self.dimensions
        dim = getattr(self.embedder, "dimension", None)
        if callable(dim):
            dim = dim()
        if dim is None:
            raise ValueError("provide dimensions= or an embedder exposing .dimension")
        return int(dim)

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        inner = self._index_cls(
            data_column,
            self._resolved_dimensions(),
            reserved_space=self.reserved_space,
            metric=self.metric,
            metadata_column=metadata_column,
            embedder=self.embedder,
        )
        return DataIndex(data_table, inner)


@dataclass
class LshKnnFactory(BruteForceKnnFactory):
    _index_cls: type = LshKnn


@dataclass
class UsearchKnnFactory(BruteForceKnnFactory):
    _index_cls: type = UsearchKnn


@dataclass
class IvfFlatKnnFactory(BruteForceKnnFactory):
    """IVF-flat retriever (HNSW-class approximate index, ``indexing/ivf.py``)."""

    nlist: int | None = None
    nprobe: int | None = None
    min_train: int = 4096

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        inner = IvfFlatKnn(
            data_column,
            self._resolved_dimensions(),
            metric=self.metric,
            metadata_column=metadata_column,
            embedder=self.embedder,
            nlist=self.nlist,
            nprobe=self.nprobe,
            min_train=self.min_train,
        )
        return DataIndex(data_table, inner)


@dataclass
class TieredKnnFactory(BruteForceKnnFactory):
    """Tiered retriever (``indexing/tiered.py``): bounded HBM hot shard over a
    host IVF cold tier — fixed device memory at any corpus size."""

    hot_rows: int | None = None
    nlist: int | None = None
    nprobe: int | None = None
    min_train: int = 4096
    promote_hits: int | None = None

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        from pathway_tpu.stdlib.indexing.nearest_neighbors import TieredKnn

        inner = TieredKnn(
            data_column,
            self._resolved_dimensions(),
            metric=self.metric,
            metadata_column=metadata_column,
            embedder=self.embedder,
            hot_rows=self.hot_rows,
            nlist=self.nlist,
            nprobe=self.nprobe,
            min_train=self.min_train,
            promote_hits=self.promote_hits,
        )
        return DataIndex(data_table, inner)


@dataclass
class TantivyBM25Factory(AbstractRetrieverFactory):
    ram_budget: int | None = None
    in_memory_index: bool = True

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        inner = TantivyBM25(
            data_column,
            metadata_column=metadata_column,
            ram_budget=self.ram_budget,
            in_memory_index=self.in_memory_index,
        )
        return DataIndex(data_table, inner)


@dataclass
class HybridIndexFactory(AbstractRetrieverFactory):
    retriever_factories: list[AbstractRetrieverFactory] = field(default_factory=list)
    k: float = 60.0

    def build_index(self, data_column, data_table, metadata_column=None) -> DataIndex:
        inners = [
            f.build_index(data_column, data_table, metadata_column).inner_index
            for f in self.retriever_factories
        ]
        return DataIndex(data_table, HybridIndex(inners, k=self.k))
