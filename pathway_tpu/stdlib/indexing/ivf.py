"""IVF-flat approximate KNN backend (the HNSW-class retriever, VERDICT r3 #7).

The reference's default big-corpus retriever is USearch HNSW
(``src/external_integration/usearch_integration.rs:20``,
``stdlib/indexing/nearest_neighbors.py:65``). A graph-walk HNSW is a pointer-
chasing structure — the exact shape that vectorizes worst — so the TPU build's
approximate index is **IVF-flat**: k-means coarse quantizer + exact scoring
inside the ``nprobe`` nearest inverted lists. Everything is dense batched
linear algebra (assign = argmax einsum, probe = einsum over CSR slices), which
keeps the implementation vectorized end to end on host numpy today and leaves
a straight path to device (the per-list score kernel is the same einsum
``ops/knn.py`` runs in HBM).

Measured (``tests/test_ivf.py``, 100k x 64 float32, 50 queries): on a
clustered corpus (mixture of 500 gaussians — the shape embedding corpora
have) the default ``nprobe`` gives **recall@10 = 1.00 at ~0.3 ms/query vs
~2.5 ms/query exact** (≈10x). On structureless random data concentration of
measure defeats IVF (recall 0.95 needs ~60% of lists probed); that regime
belongs to ``BruteForceKnn``'s HBM einsum, and the docstrings say so.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.internals.keys import tie_order_u64
from pathway_tpu.stdlib.indexing._engine import IndexBackend


class IvfFlatBackend(IndexBackend):
    """Inverted-file flat index: train k-means centroids over the corpus,
    assign every vector to its nearest list, search only the ``nprobe``
    closest lists exactly.

    Lifecycle: below ``min_train`` vectors search is exact brute force (small
    corpora don't benefit from pruning); the first search at or past
    ``min_train`` trains the quantizer; the quantizer retrains when the corpus
    doubles past its training size (assignments drift as data grows).
    """

    #: per-shard top-k partials merge like the brute-force backend's
    shardable = True

    def __init__(
        self,
        dimension: int,
        metric: str = "cos",
        nlist: int | None = None,
        nprobe: int | None = None,
        min_train: int = 4096,
        seed: int = 0,
    ):
        if metric not in ("cos", "dot", "l2sq"):
            raise ValueError(f"unsupported metric {metric!r}")
        self.dimension = dimension
        self.metric = metric
        self.nlist_cfg = nlist
        self.nprobe_cfg = nprobe
        self.min_train = min_train
        self.seed = seed
        cap = 1024
        self._vecs = np.zeros((cap, dimension), dtype=np.float32)
        self._keys = np.zeros(cap, dtype=np.uint64)
        self._live = np.zeros(cap, dtype=bool)
        self._n = 0  # rows used (live + dead)
        self._n_live = 0
        self._slot_of: dict[int, int] = {}
        self.metadata: dict[int, Any] = {}
        # quantizer state
        self._centroids: np.ndarray | None = None
        self._assign = np.full(cap, -1, dtype=np.int32)
        self._trained_at = 0  # corpus size at last train
        self._free: list[int] = []  # dead slots recycled by add (bounds _n)
        # CSR layout is rebuilt only when enough has churned; in between,
        # removals mask rows (``_csr_alive``) and additions land in a small
        # exactly-scored tail (``_extra``) — a one-row delta per tick must not
        # pay an O(N) re-sort + full-corpus copy
        self._csr_dirty = True
        self._list_order: np.ndarray | None = None  # slots grouped by list
        self._list_starts: np.ndarray | None = None
        self._csr_alive: np.ndarray | None = None
        self._csr_pos: dict[int, int] = {}  # slot -> csr row
        self._extra: set[int] = set()  # slots added since the last rebuild
        self._csr_dead = 0
        #: per-(query, list) candidate over-fetch multiplier so post-filtering
        #: still fills k; callers that filter elsewhere (the tiered backend
        #: rescores and filters at its merge) set 1 to skip the margin
        self.post_filter_mult = 10

    # ------------------------------------------------------------------ sizing
    def __len__(self) -> int:
        return self._n_live

    @property
    def nlist(self) -> int:
        if self.nlist_cfg is not None:
            return max(1, self.nlist_cfg)
        return max(1, int(np.sqrt(max(self._n_live, 1))))

    def _nprobe(self, nlist: int) -> int:
        if self.nprobe_cfg is not None:
            return min(nlist, max(1, self.nprobe_cfg))
        # default tuned on clustered (embedding-like) corpora: recall@10 = 1.0
        # at 10x under exact scoring on 100k x 64 (tests/test_ivf.py). On
        # STRUCTURELESS data concentration of measure defeats any IVF —
        # measured recall@10 = 0.95 needs nprobe ~ 0.6*nlist there, at which
        # point brute force is the right tool: raise nprobe= explicitly or use
        # BruteForceKnn for unclustered corpora.
        return min(nlist, max(8, nlist // 16))

    # ------------------------------------------------------------------ writes
    def _norm(self, v: np.ndarray) -> np.ndarray:
        if self.metric != "cos":
            return v
        n = np.linalg.norm(v, axis=-1, keepdims=True)
        return v / np.maximum(n, 1e-12)

    def _grow(self) -> None:
        cap = len(self._keys) * 2
        for name in ("_vecs", "_keys", "_live", "_assign"):
            arr = getattr(self, name)
            shape = (cap,) + arr.shape[1:]
            fill = -1 if name == "_assign" else 0
            new = np.full(shape, fill, dtype=arr.dtype)
            new[: len(arr)] = arr
            setattr(self, name, new)

    def add(self, key: int, item: Any, metadata: Any) -> None:
        if key in self._slot_of:
            self.remove(key)
        v = self._norm(np.asarray(item, dtype=np.float32).reshape(-1))
        if v.shape[0] != self.dimension:
            raise ValueError(
                f"vector dimension {v.shape[0]} != index dimension {self.dimension}"
            )
        if self._free:
            slot = self._free.pop()
        else:
            if self._n == len(self._keys):
                self._grow()
            slot = self._n
            self._n += 1
        self._n_live += 1
        self._vecs[slot] = v
        self._keys[slot] = np.uint64(key)
        self._live[slot] = True
        self._slot_of[key] = slot
        self.metadata[key] = metadata
        if self._centroids is not None:
            self._assign[slot] = int(
                np.argmax(self._centroid_scores(v[None, :])[0])
            )
            if self._list_order is not None and not self._csr_dirty:
                self._extra.add(slot)
                self._maybe_dirty()
        else:
            self._csr_dirty = True

    def remove(self, key: int) -> None:
        slot = self._slot_of.pop(key, None)
        if slot is None:
            return
        self._live[slot] = False
        self._n_live -= 1
        self._free.append(slot)
        self.metadata.pop(key, None)
        if self._list_order is not None and not self._csr_dirty:
            # tail membership first: a recycled slot may also have a STALE
            # (already-masked) row in the CSR from its previous life
            if slot in self._extra:
                self._extra.discard(slot)
            else:
                pos = self._csr_pos.get(slot)
                if pos is not None:
                    self._csr_alive[pos] = False
                    self._csr_dead += 1
            self._maybe_dirty()
        else:
            self._csr_dirty = True

    def _maybe_dirty(self) -> None:
        churn = len(self._extra) + self._csr_dead
        if churn > max(1024, self._n_live // 10):
            self._csr_dirty = True

    # ------------------------------------------------------------------ training
    def _pairwise(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """(len_a, len_b) similarity matrix under the index metric (higher =
        closer) — the ONE scoring formula (assignment, probing, tail, train
        all route here so the metric cannot drift between paths)."""
        if self.metric == "l2sq":
            return (
                2.0 * (a @ b.T)
                - (a * a).sum(axis=1)[:, None]
                - (b * b).sum(axis=1)[None, :]  # true -||a-b||^2
            )
        return a @ b.T

    def _centroid_scores(self, q: np.ndarray) -> np.ndarray:
        """(q, nlist) similarity of queries to centroids (higher = closer)."""
        return self._pairwise(q, self._centroids)

    def _train(self) -> None:
        """Vectorized Lloyd's k-means (few iterations; subsampled)."""
        live = np.flatnonzero(self._live[: self._n])
        nlist = self.nlist
        rng = np.random.default_rng(self.seed)
        sample = live
        if len(sample) > 50_000:
            sample = rng.choice(sample, 50_000, replace=False)
        x = self._vecs[sample]
        if nlist >= len(sample):
            cents = x.copy()
        else:
            cents = x[rng.choice(len(x), nlist, replace=False)].copy()
            for _ in range(8):
                a = np.argmax(self._pairwise(x, cents), axis=1)
                counts = np.bincount(a, minlength=len(cents)).astype(np.float32)
                sums = np.zeros_like(cents)
                np.add.at(sums, a, x)
                nonempty = counts > 0
                cents[nonempty] = sums[nonempty] / counts[nonempty, None]
                # re-seed empty centroids from random points
                n_empty = int((~nonempty).sum())
                if n_empty:
                    cents[~nonempty] = x[rng.choice(len(x), n_empty)]
                if self.metric == "cos":
                    cents = self._norm(cents)
        self._centroids = np.ascontiguousarray(cents, dtype=np.float32)
        # assign ALL live rows
        scores = self._centroid_scores(self._vecs[live])
        self._assign[: self._n] = -1
        self._assign[live] = np.argmax(scores, axis=1).astype(np.int32)
        self._trained_at = self._n_live
        self._csr_dirty = True

    def _ensure_trained(self) -> bool:
        if self._n_live < self.min_train:
            return False
        if self._centroids is None or self._n_live >= 2 * max(self._trained_at, 1):
            self._train()
        return True

    def _csr(self) -> tuple[np.ndarray, np.ndarray]:
        """List-major layout: live vectors regrouped CONTIGUOUSLY by inverted
        list (``_vecs_csr``), so probing a list is a slice matmul, not a
        gather — the host analogue of keeping HBM reads coalesced."""
        if not self._csr_dirty and self._list_order is not None:
            return self._list_order, self._list_starts
        live = np.flatnonzero(self._live[: self._n])
        a = self._assign[live]
        order = np.argsort(a, kind="stable")
        slots = live[order]
        a_sorted = a[order]
        nlist = len(self._centroids)
        starts = np.searchsorted(a_sorted, np.arange(nlist + 1))
        self._list_order, self._list_starts = slots, starts
        self._vecs_csr = np.ascontiguousarray(self._vecs[slots])
        self._keys_csr = self._keys[slots]
        self._tie_csr = tie_order_u64(self._keys_csr)
        self._csr_alive = np.ones(len(slots), dtype=bool)
        self._csr_pos = {int(s): i for i, s in enumerate(slots)}
        self._extra = set()
        self._csr_dead = 0
        self._csr_dirty = False
        return slots, starts

    # ------------------------------------------------------------------ search
    def _score(self, q: np.ndarray, slots: np.ndarray) -> np.ndarray:
        return self._pairwise(self._vecs[slots], q[None, :])[:, 0]

    def _top(self, q, slots, k, flt):
        scores = self._score(q, slots)
        # canonical order: score desc, tie_order asc (matches ops/knn.py)
        keys = self._keys[slots]
        order = np.lexsort((tie_order_u64(keys), -scores))
        picked = []
        for i in order:
            if len(picked) >= k:
                break
            key = int(keys[i])
            if flt(self.metadata.get(key)):
                picked.append((key, float(scores[i])))
        return picked

    def search(self, items, ks, filters):
        if not items:
            return []
        if self._n_live == 0:
            return [[] for _ in items]
        qs = self._norm(np.stack([np.asarray(q, dtype=np.float32) for q in items]))
        if not self._ensure_trained():
            # exact path for small corpora
            live = np.flatnonzero(self._live[: self._n])
            return [
                self._top(q, live, k, flt) for q, k, flt in zip(qs, ks, filters)
            ]
        _, starts = self._csr()
        nlist = len(self._centroids)
        nprobe = self._nprobe(nlist)
        cscores = self._centroid_scores(qs)
        probe = np.argpartition(-cscores, min(nprobe, nlist) - 1, axis=1)[:, :nprobe]
        nq = len(qs)
        # over-fetch per (query, list) so post-filtering still fills k (same
        # 10x factor as VectorBackend.search; 1 when the caller filters later)
        fetch = max(ks, default=1) * getattr(self, "post_filter_mult", 10)
        # batch by LIST across queries: one slice matmul per probed list (big
        # contiguous GEMMs instead of per-query gathers)
        q_of_list: dict[int, list[int]] = {}
        for qi in range(nq):
            for li in probe[qi]:
                q_of_list.setdefault(int(li), []).append(qi)
        partial_pos: list[list[np.ndarray]] = [[] for _ in range(nq)]
        partial_scores: list[list[np.ndarray]] = [[] for _ in range(nq)]
        for li, q_idx in q_of_list.items():
            s, e = int(starts[li]), int(starts[li + 1])
            if s == e:
                continue
            block = self._vecs_csr[s:e]
            scores = self._pairwise(block, qs[q_idx])  # (len, |q_idx|)
            dead = ~self._csr_alive[s:e]
            if dead.any():  # rows removed since the last CSR rebuild
                scores[dead] = -np.inf
            m = e - s
            top = min(fetch, m)
            if top < m:
                sel = np.argpartition(-scores, top - 1, axis=0)[:top]
            else:
                sel = np.tile(np.arange(m)[:, None], (1, len(q_idx)))
            for col, qi in enumerate(q_idx):
                rows = sel[:, col]
                partial_pos[qi].append(rows + s)
                partial_scores[qi].append(scores[rows, col])
        # the un-indexed tail (added since the last CSR rebuild, bounded by
        # _maybe_dirty): scored exactly against every query
        tail = sorted(self._extra)
        tail_keys = tail_ties = tail_scores = None
        if tail:
            tslots = np.asarray(tail, dtype=np.int64)
            tblock = self._vecs[tslots]
            tail_scores = self._pairwise(tblock, qs)
            tail_keys = self._keys[tslots]
            tail_ties = tie_order_u64(tail_keys)
        out = []
        for qi, (k, flt) in enumerate(zip(ks, filters)):
            pos_parts = partial_pos[qi]
            keys_parts = [self._keys_csr[p] for p in pos_parts]
            tie_parts = [self._tie_csr[p] for p in pos_parts]
            score_parts = list(partial_scores[qi])
            if tail:
                keys_parts.append(tail_keys)
                tie_parts.append(tail_ties)
                score_parts.append(tail_scores[:, qi])
            if not keys_parts:
                out.append([])
                continue
            keys = np.concatenate(keys_parts)
            ties = np.concatenate(tie_parts)
            scores = np.concatenate(score_parts)
            # canonical order: score desc, tie_order asc (matches ops/knn.py)
            order = np.lexsort((ties, -scores))
            picked = []
            for i in order:
                if len(picked) >= k:
                    break
                if scores[i] == -np.inf:
                    break  # only masked-dead rows remain
                key = int(keys[i])
                if flt(self.metadata.get(key)):
                    picked.append((key, float(scores[i])))
            out.append(picked)
        return out
