from .impl import (
    exact_modularity,
    louvain_communities,
    louvain_level,
)

__all__ = ["exact_modularity", "louvain_communities", "louvain_level"]
