"""Louvain community detection (reference:
``python/pathway/stdlib/graphs/louvain_communities/impl.py``).

One level = fixed-point (``pw.iterate``) of parallel-safe greedy moves: every
vertex scores each adjacent cluster with the (unnormalized, ×m) modularity gain

    2·w(v→C) − deg(v)·(2·deg(C \\ {v}) + deg(v)) / m

takes the argmax, and a move executes only when the vertex holds the maximum
deterministic priority in both its source and target clusters among this round's
candidate movers — so no cluster participates in two simultaneous moves and the
objective increases monotonically. ``louvain_communities`` stacks levels by
contracting each level's clustering into a weighted cluster graph.

Undirected graphs are represented as both directed arcs, as in the reference.
"""

from __future__ import annotations

import pathway_tpu as pw
from pathway_tpu.internals.fingerprints import fingerprint
from pathway_tpu.stdlib.utils.filtering import argmax_rows

from ..graph import WeightedGraph


def _one_step(WE: pw.Table, clustering: pw.Table) -> pw.Table:
    """One round of parallel-safe greedy moves. ``WE``: (u, v, weight) arcs;
    ``clustering``: per-vertex cluster pointer ``c``. Returns the new clustering."""
    total = WE.reduce(m=pw.reducers.sum(WE.weight))

    # vertex degrees (sum of outgoing arc weights; undirected graphs store both
    # arcs so this is the full incident weight). Default 0 for isolated vertices.
    out_deg = WE.groupby(id=WE.u).reduce(degree=pw.reducers.sum(WE.weight))
    degrees = clustering.select(degree=0.0).update_rows(out_deg).with_universe_of(clustering)

    # cluster degree sums
    member = clustering.select(c=pw.this.c, degree=degrees.ix(clustering.id).degree)
    cluster_deg = member.groupby(id=member.c).reduce(
        cdeg=pw.reducers.sum(member.degree)
    )

    # weight from each vertex to each adjacent cluster (self-loops excluded from
    # adjacency; their weight is invariant under any move of v)
    arcs = WE.filter(WE.u != WE.v)
    to_cluster = arcs.select(u=arcs.u, c=clustering.ix(arcs.v).c, w=arcs.weight)
    # ensure the current cluster is always a candidate, even with zero edges to it
    stay = clustering.select(u=clustering.id, c=clustering.c, w=0.0).with_id_from(
        pw.this.u, pw.this.c
    )
    linked = to_cluster.groupby(to_cluster.u, to_cluster.c).reduce(
        to_cluster.u, to_cluster.c, w=pw.reducers.sum(to_cluster.w)
    )
    candidates = stay.update_rows(linked)

    gains = candidates.select(
        u=candidates.u,
        c=candidates.c,
        gain=2.0 * candidates.w
        - degrees.ix(candidates.u).degree
        * (
            2.0
            * (
                cluster_deg.ix(candidates.c).cdeg
                # leaving-adjustment: when scoring the current cluster, the
                # vertex's own degree is not part of the surrounding mass
                - pw.if_else(
                    clustering.ix(candidates.u).c == candidates.c,
                    degrees.ix(candidates.u).degree,
                    0.0,
                )
            )
            + degrees.ix(candidates.u).degree
        )
        / total.ix_ref(context=candidates).m,
    )

    best = argmax_rows(gains, gains.u, what=gains.gain)
    # move priority is salted with the vertex's CURRENT cluster: every executed
    # move re-randomizes the winner's priority next round (no fixed-priority
    # starvation, the reference's fingerprint((x, iter)) intent) while staying
    # constant at the fixed point so pw.iterate still converges
    annotated = best.select(
        u=best.u,
        vc=best.c,
        uc=clustering.ix(best.u).c,
        r=pw.apply_with_type(
            lambda k, c: fingerprint((k, c), format="i64"), int, best.u, clustering.ix(best.u).c
        ),
    )
    movers = annotated.filter(annotated.vc != annotated.uc)

    # independent set: a move runs only if its priority is the max in both the
    # source and the target cluster among this round's movers
    touched = pw.Table.concat_reindex(
        movers.select(c=movers.uc, r=movers.r),
        movers.select(c=movers.vc, r=movers.r),
    )
    cluster_max = argmax_rows(touched, touched.c, what=touched.r).with_id(pw.this.c)
    checked = movers.select(
        u=movers.u,
        vc=movers.vc,
        r=movers.r,
        src_max=cluster_max.ix(movers.uc).r,
        dst_max=cluster_max.ix(movers.vc).r,
    )
    safe = checked.filter((checked.r == checked.src_max) & (checked.r == checked.dst_max))

    delta = safe.with_id(safe.u).select(c=pw.this.vc)
    return clustering.update_rows(delta).with_universe_of(clustering)


def louvain_level(G: WeightedGraph, iteration_limit: int | None = None) -> pw.Table:
    """Clustering that is a local maximum of the louvain objective for ``G``."""
    initial = G.V.select(c=G.V.id)
    return pw.iterate(
        lambda clustering, WE: dict(clustering=_one_step(WE, clustering)),
        iteration_limit=iteration_limit,
        clustering=initial,
        WE=G.WE,
    ).clustering


def louvain_communities(
    G: WeightedGraph, levels: int = 1, iteration_limit: int | None = 64
) -> pw.Table:
    """Multi-level louvain: run a level, contract clusters to a weighted graph,
    repeat. Returns the final vertex → community assignment (column ``c``)."""
    assignment = None  # vertex -> current-level cluster
    level_graph = G
    for _ in range(levels):
        clustering = louvain_level(level_graph, iteration_limit=iteration_limit)
        if assignment is None:
            assignment = clustering
        else:
            assignment = assignment.select(c=clustering.ix(assignment.c).c)
        level_graph = level_graph.contracted_to_weighted_simple_graph(clustering)
    return assignment


def exact_modularity(G: WeightedGraph, C: pw.Table, round_digits: int = 12) -> pw.Table:
    """Modularity of clustering ``C`` on ``G`` (testing helper): per cluster,
    (internal·m − deg²) / m², summed. Arc convention: both directions stored, so
    m and degrees already count each undirected edge twice."""
    clusters = C.groupby(id=C.c).reduce()

    deg_rows = (
        G.WE.select(c=C.ix(G.WE.u).c, w=G.WE.weight)
        .groupby(id=pw.this.c)
        .reduce(degree=pw.reducers.sum(pw.this.w))
    )
    cluster_degree = clusters.select(degree=0.0).update_rows(deg_rows).with_universe_of(clusters)

    internal_rows = (
        G.WE.select(cu=C.ix(G.WE.u).c, cv=C.ix(G.WE.v).c, w=G.WE.weight)
        .filter(pw.this.cu == pw.this.cv)
        .groupby(id=pw.this.cu)
        .reduce(internal=pw.reducers.sum(pw.this.w))
    )
    cluster_internal = clusters.select(internal=0.0).update_rows(internal_rows).with_universe_of(clusters)

    total = G.WE.reduce(m=pw.reducers.sum(G.WE.weight))

    scores = clusters.select(
        q=pw.apply_with_type(
            lambda internal, degree, m: (internal * m - degree * degree) / (m * m),
            float,
            cluster_internal.ix(clusters.id).internal,
            cluster_degree.ix(clusters.id).degree,
            total.ix_ref(context=clusters).m,
        )
    )
    return scores.reduce(
        modularity=pw.apply_with_type(
            lambda s: round(s, round_digits), float, pw.reducers.sum(scores.q)
        )
    )
