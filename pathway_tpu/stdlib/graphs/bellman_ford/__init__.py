from .impl import Dist, DistFromSource, Vertex, bellman_ford

__all__ = ["Dist", "DistFromSource", "Vertex", "bellman_ford"]
