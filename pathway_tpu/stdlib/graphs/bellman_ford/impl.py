"""Single-source shortest paths via fixed-point relaxation (reference:
``python/pathway/stdlib/graphs/bellman_ford/impl.py``).

Each round every vertex keeps the minimum of its current distance and the best
relaxation over incoming edges; ``pw.iterate`` drives the rounds to quiescence.
Monotone non-increasing distances guarantee convergence on graphs without
negative cycles.
"""

from __future__ import annotations

import math

import pathway_tpu as pw



class Vertex(pw.Schema):
    is_source: bool


class Dist(pw.Schema):
    dist: float


class DistFromSource(pw.Schema):
    dist_from_source: float


def _relax(vertices_dist: pw.Table, edges: pw.Table) -> pw.Table:
    # candidates: keep the current distance, plus one candidate per incoming edge
    own = vertices_dist.select(
        target=vertices_dist.id, d=vertices_dist.dist_from_source
    )
    via = edges.select(
        target=edges.v,
        d=vertices_dist.ix(edges.u).dist_from_source + edges.dist,
    )
    candidates = pw.Table.concat_reindex(own, via)
    return candidates.groupby(id=candidates.target).reduce(
        dist_from_source=pw.reducers.min(candidates.d)
    )


def bellman_ford(vertices: pw.Table, edges: pw.Table) -> pw.Table:
    """``vertices``: rows with ``is_source``; ``edges``: rows with pointer
    endpoints ``u``, ``v`` and length ``dist``. Returns per-vertex
    ``dist_from_source`` (inf when unreachable)."""
    initial = vertices.select(
        dist_from_source=pw.if_else(vertices.is_source, 0.0, math.inf)
    )
    return pw.iterate(
        lambda dists, edges: _relax(dists, edges),
        dists=pw.iterate_universe(initial),
        edges=edges,
    )
