from .impl import Result, pagerank

__all__ = ["Result", "pagerank"]
