"""PageRank over the incremental dataflow (reference:
``python/pathway/stdlib/graphs/pagerank/impl.py``).

Integer-arithmetic ranks (damping 5/6 scaled by 1000) so the fixed point is exact
and incremental updates are deterministic. Implemented on ``pw.iterate`` with
``iteration_limit=steps`` — idiomatic here, where the reference unrolls a Python
loop of ``steps`` dataflow copies.
"""

from __future__ import annotations

import pathway_tpu as pw



class Result(pw.Schema):
    rank: int


def pagerank(edges: pw.Table, steps: int = 5) -> pw.Table:
    """Ranks for every vertex appearing as an edge endpoint."""
    # vertex set = union of endpoints; out-degree counts only outgoing edges
    targets = edges.groupby(id=edges.v).reduce(degree=0)
    sources = edges.groupby(id=edges.u).reduce(degree=pw.reducers.count())
    degrees = pw.Table.update_rows(targets, sources)

    initial = degrees.select(rank=6_000)

    def step(ranks: pw.Table, edges: pw.Table, degrees: pw.Table) -> pw.Table:
        # if_else evaluates both branches; guard the divisor so sinks (degree 0)
        # don't floor-divide by zero
        outflow = degrees.select(
            flow=pw.if_else(
                degrees.degree == 0,
                0,
                (ranks.ix(degrees.id, context=degrees).rank * 5)
                // (pw.if_else(degrees.degree == 0, 1, degrees.degree) * 6),
            )
        )
        contrib = edges.select(target=edges.v, flow=outflow.ix(edges.u).flow)
        collected = contrib.groupby(id=contrib.target).reduce(
            inflow=pw.reducers.sum(contrib.flow)
        )
        # vertices with no in-edges keep only the teleport mass
        base = degrees.select(inflow=0)
        return pw.Table.update_rows(base, collected).select(
            rank=pw.this.inflow + 1_000
        )

    return pw.iterate(
        lambda ranks, edges, degrees: step(ranks, edges, degrees),
        iteration_limit=steps,
        ranks=initial,
        edges=edges,
        degrees=degrees,
    )
