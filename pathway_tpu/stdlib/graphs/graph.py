"""Graph containers + cluster contraction (reference:
``python/pathway/stdlib/graphs/graph.py``).

A ``Graph`` is a pair of tables (vertices ``V``, directed edges ``E`` with pointer
endpoints); ``WeightedGraph`` adds a weighted edge table ``WE``. Contraction maps a
clustering (vertex → cluster pointer) over the edge endpoints and re-groups
parallel edges, which is how louvain builds its next level.
"""

from __future__ import annotations

from dataclasses import dataclass

import pathway_tpu as pw



def _full_clustering(
    vertices: pw.Table, clustering: pw.Table
) -> pw.Table:
    """Extend a partial clustering so unassigned vertices sit in singleton
    clusters keyed by their own id."""
    return vertices.select(c=vertices.id).update_rows(clustering)


@dataclass
class Graph:
    """Undirected, unweighted (multi)graph."""

    V: pw.Table
    E: pw.Table

    def contracted_to_multi_graph(self, clustering: pw.Table) -> "Graph":
        full = _full_clustering(self.V, clustering)
        new_V = (
            full.groupby(full.c).reduce(v=full.c).with_id(pw.this.v).select()
        )
        new_E = self.E.select(u=full.ix(self.E.u).c, v=full.ix(self.E.v).c)
        return Graph(new_V, new_E)

    def contracted_to_simple_graph(self, clustering: pw.Table) -> "Graph":
        g = self.contracted_to_multi_graph(clustering)
        g.E = g.E.groupby(g.E.u, g.E.v).reduce(g.E.u, g.E.v)
        return g

    def without_self_loops(self) -> "Graph":
        return Graph(self.V, self.E.filter(self.E.u != self.E.v))


@dataclass
class WeightedGraph(Graph):
    """Graph with a weighted edge table ``WE`` (columns u, v, weight)."""

    WE: pw.Table = None

    @staticmethod
    def from_vertices_and_weighted_edges(V: pw.Table, WE: pw.Table) -> "WeightedGraph":
        return WeightedGraph(V, WE, WE)

    def contracted_to_weighted_simple_graph(self, clustering: pw.Table) -> "WeightedGraph":
        full = _full_clustering(self.V, clustering)
        new_V = (
            full.groupby(full.c).reduce(v=full.c).with_id(pw.this.v).select()
        )
        mapped = self.WE.select(
            u=full.ix(self.WE.u).c, v=full.ix(self.WE.v).c, weight=self.WE.weight
        )
        new_WE = mapped.groupby(mapped.u, mapped.v).reduce(
            mapped.u, mapped.v, weight=pw.reducers.sum(mapped.weight)
        )
        return WeightedGraph.from_vertices_and_weighted_edges(new_V, new_WE)

    def without_self_loops(self) -> "WeightedGraph":
        return WeightedGraph.from_vertices_and_weighted_edges(
            self.V, self.WE.filter(self.WE.u != self.WE.v)
        )
