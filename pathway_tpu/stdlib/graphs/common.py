"""Shared graph schemas (reference: ``python/pathway/stdlib/graphs/common.py``)."""

from __future__ import annotations

import pathway_tpu as pw


class Vertex(pw.Schema):
    pass


class Edge(pw.Schema):
    """Directed edge between vertex rows, endpoints stored as row pointers."""

    u: pw.Pointer
    v: pw.Pointer


class Weight(pw.Schema):
    """Weight column mixin for vertices/edges."""

    weight: float


class Cluster(Vertex):
    pass


class Clustering(pw.Schema):
    """Membership relation: the row's id (a vertex) belongs to cluster ``c``."""

    c: pw.Pointer
