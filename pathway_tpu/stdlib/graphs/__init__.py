"""Placeholder — implemented in a later milestone."""
