"""Graph algorithms over the incremental dataflow (reference:
``python/pathway/stdlib/graphs/``): bellman-ford, pagerank, louvain — the
``pw.iterate`` fixed-point exercisers."""

from __future__ import annotations

from . import bellman_ford, louvain_communities, pagerank
from .common import Clustering, Edge, Vertex, Weight
from .graph import Graph, WeightedGraph

__all__ = [
    "bellman_ford",
    "pagerank",
    "louvain_communities",
    "Clustering",
    "Edge",
    "Vertex",
    "Weight",
    "Graph",
    "WeightedGraph",
]
