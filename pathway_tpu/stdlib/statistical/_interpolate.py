"""Linear interpolation over a timestamp-ordered column
(reference: ``python/pathway/stdlib/statistical/_interpolate.py``).

Design difference from the reference: its fixed point copies the nearest known
value one *hop* per round (O(gap) rounds for a gap of missing rows). Here each
round also **jumps the pointer** to the neighbor's pointer (pointer doubling),
so a gap of g rows converges in O(log g) rounds of the ``pw.iterate`` engine —
the classic parallel list-ranking trick, which matters when the fixed point is
a dataflow round, not a loop iteration.
"""

from __future__ import annotations

from enum import Enum

import pathway_tpu as pw


class InterpolateMode(Enum):
    LINEAR = 0


def _missing(v) -> bool:
    # Optional[float] columns store None as NaN; both mean "no value here"
    return v is None or (isinstance(v, float) and v != v)


def _propagate(t: pw.Table) -> pw.Table:
    prev_row = t.ix(t.prev_ptr, optional=True)
    next_row = t.ix(t.next_ptr, optional=True)
    return t.select(
        # adopt the neighbor's known (t, v) when ours is missing
        prev_t=pw.coalesce(t.prev_t, prev_row.prev_t),
        prev_v=pw.coalesce(t.prev_v, prev_row.prev_v),
        next_t=pw.coalesce(t.next_t, next_row.next_t),
        next_v=pw.coalesce(t.next_v, next_row.next_v),
        # pointer doubling: if still unresolved, look twice as far next round
        prev_ptr=pw.if_else(t.prev_v.is_not_none(), t.prev_ptr, prev_row.prev_ptr),
        next_ptr=pw.if_else(t.next_v.is_not_none(), t.next_ptr, next_row.next_ptr),
    )


def _nearest_known(table: pw.Table, ts_ref, value_ref) -> pw.Table:
    """Per row: timestamp+value of the nearest known (non-None) row on each
    side, itself included — rows with a value resolve to themselves, which is
    fine because ``lerp`` short-circuits on them."""
    ordered = table.sort(key=ts_ref)
    known_t = pw.apply(lambda t, v: None if _missing(v) else float(t), ts_ref, value_ref)
    known_v = pw.apply(lambda v: None if _missing(v) else float(v), value_ref)
    seeded = ordered.select(
        prev_ptr=ordered.prev,
        next_ptr=ordered.next,
        prev_t=known_t,
        prev_v=known_v,
        next_t=known_t,
        next_v=known_v,
    )
    # iterate preserves row keys; re-assert the universe for same-universe selects
    return pw.iterate(_propagate, t=seeded).with_universe_of(table)


def interpolate(
    self: pw.Table,
    timestamp,
    *values,
    mode: InterpolateMode = InterpolateMode.LINEAR,
):
    """Fill None values of ``*values`` columns by linear interpolation between
    the nearest known neighbors in ``timestamp`` order; boundary gaps take the
    single known neighbor."""
    if mode != InterpolateMode.LINEAR:
        raise ValueError(
            "interpolate: Invalid mode. Only InterpolateMode.LINEAR is currently available."
        )
    ts_ref = self._bind(timestamp)
    out = self
    for v in values:
        v_ref = self._bind(v)
        near = _nearest_known(self, ts_ref, v_ref)

        def lerp(t, v, t_prev, v_prev, t_next, v_next):
            if not _missing(v):
                return float(v)
            if _missing(v_prev) and _missing(v_next):
                return None
            if _missing(v_prev):
                return v_next
            if _missing(v_next):
                return v_prev
            if t_next == t_prev:
                return v_prev
            return v_prev + (float(t) - t_prev) * (v_next - v_prev) / (t_next - t_prev)

        filled = pw.apply(
            lerp, ts_ref, v_ref, near.prev_t, near.prev_v, near.next_t, near.next_v
        )
        out = out.with_columns(**{v_ref.name: filled})
    return out
