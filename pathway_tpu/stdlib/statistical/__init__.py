"""Statistical stdlib (reference: ``python/pathway/stdlib/statistical/``)."""

from pathway_tpu.stdlib.statistical._interpolate import InterpolateMode, interpolate

__all__ = ["InterpolateMode", "interpolate"]
