"""Standard library (reference: ``python/pathway/stdlib/`` — temporal, indexing, ml,
graphs, stateful, ordered, statistical, utils, viz)."""

from pathway_tpu.stdlib import (
    graphs,
    indexing,
    ml,
    ordered,
    stateful,
    statistical,
    temporal,
    utils,
    viz,
)

__all__ = [
    "graphs",
    "indexing",
    "ml",
    "ordered",
    "stateful",
    "statistical",
    "temporal",
    "utils",
    "viz",
]
