"""Ordered utilities: ``Table.diff`` (reference ``stdlib/ordered/diff.py``).

``diff(timestamp, *values)`` computes, per row, ``value - previous value`` in
``timestamp`` order (per ``instance``), via the sorted prev/next structure
(``internals/sorting.py``) and pointer chasing with ``ix``.
"""

from __future__ import annotations


def diff_impl(table, timestamp, *values, instance=None):
    ts = table._bind(timestamp)
    inst = table._bind(instance) if instance is not None else None
    sorted_ptrs = table.sort(ts, instance=inst) if inst is not None else table.sort(ts)
    with_prev = table.with_columns(__prev=sorted_ptrs.prev)
    prev_rows = table.ix(with_prev["__prev"], optional=True)
    out = {}
    for v in values:
        ref = table._bind(v)
        out[f"diff_{ref.name}"] = ref - prev_rows[ref.name]  # reference naming
    return table.select(**out)


__all__ = ["diff_impl"]
