"""Table visualization (reference: ``stdlib/viz/`` — bokeh/panel notebook
widgets). The interactive bokeh dashboard is dependency-gated (bokeh is not in
this image); ``show``/``table_viz`` fall back to a live pandas snapshot that
notebooks render and re-render via :class:`pw.LiveTable`."""

from __future__ import annotations

from typing import Any


def show(table: Any, **kwargs: Any):
    """Display a (live) snapshot of the table. In interactive mode returns a
    :class:`pw.LiveTable` that keeps updating; otherwise computes and prints
    the current rows."""
    try:
        import bokeh  # noqa: F401

        import warnings

        warnings.warn(
            "bokeh dashboards are not wired yet; showing the LiveTable/print "
            "fallback instead",
            stacklevel=2,
        )
    except ImportError:
        pass
    from pathway_tpu.internals.interactive import is_interactive_mode_enabled, live

    if is_interactive_mode_enabled():
        return live(table)
    from pathway_tpu.debug import compute_and_print

    compute_and_print(table)
    return None


table_viz = show
