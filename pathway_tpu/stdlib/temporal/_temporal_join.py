"""Temporal joins: interval_join, asof_join, asof_now_join.

Behavior parity with the reference's ``stdlib/temporal/_interval_join.py:577-1404``
and ``_asof_join.py:479-1000`` / ``_asof_now_join.py``, re-designed for the block
engine: one stateful ``TemporalJoinNode`` holds both sides' rows grouped by join key
(plus a time bucket for interval joins, bounding recompute), re-derives the touched
groups' matched pairs per tick, and emits only the delta vs what it previously
emitted. Outer modes track per-row match counts and maintain padded emissions
(reference: outer interval joins via universe subtraction; here it's node-local
bookkeeping). ``asof_now_join`` is a separate append-only-left node: each query row
is answered against the right state at its arrival tick and never revised
(the as-of-now discipline that makes request/response serving work, SURVEY §3.3).

The result objects subclass ``JoinResult`` so ``select``/``filter`` with
``pw.left``/``pw.right`` work unchanged.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import expression as expr_mod
from pathway_tpu.internals import schema as schema_mod
from pathway_tpu.internals import thisclass
from pathway_tpu.internals.joins import JoinResult
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.internals.table import Table
from pathway_tpu.internals.universe import Universe
from pathway_tpu.stdlib.temporal.behaviors import CommonBehavior, apply_temporal_behavior

_PAIR_SALT = np.uint64(0x9E3779B97F4A7C15)


def _mix_pair(a: int, b: int) -> int:
    from pathway_tpu.internals.keys import combine_keys

    return int(combine_keys(np.asarray([a], np.uint64), np.asarray([b], np.uint64))[0])


@np.errstate(over="ignore")
def _pad_key(k: int, side: int) -> int:
    return _mix_pair(k, side ^ int(_PAIR_SALT))


class _Side:
    __slots__ = ("rows", "info")

    def __init__(self):
        self.rows: dict[int, tuple] = {}  # key -> values
        self.info: dict[int, tuple[Any, Any]] = {}  # key -> (jk, t)


class TemporalJoinNode(Node):
    """Matcher-parameterized incremental two-input temporal join."""

    name = "temporal_join"

    snapshot_attrs = (
        "left", "right", "_groups", "_group_pairs", "_pair_rows",
        "_match_count_l", "_match_count_r", "_pads_l", "_pads_r",
    )

    def exchange_key(self, port):
        return lambda batch: batch.data["__jk__"].astype(np.uint64)

    def __init__(
        self,
        n_left_cols: int,
        n_right_cols: int,
        how: str,
        matcher: str,  # "interval" | "asof"
        lower: Any = None,
        upper: Any = None,
        direction: str = "backward",
    ):
        super().__init__(n_inputs=2)
        self.n_left_cols = n_left_cols
        self.n_right_cols = n_right_cols
        self.how = how
        self.matcher = matcher
        self.lower = lower
        self.upper = upper
        self.direction = direction
        self.left = _Side()
        self.right = _Side()
        # group key -> (set of left keys, set of right keys)
        self._groups: dict[Any, tuple[set, set]] = {}
        # pair bookkeeping
        self._group_pairs: dict[Any, set[int]] = {}  # group -> emitted pair ids
        self._pair_rows: dict[int, tuple] = {}
        self._match_count_l: dict[int, int] = {}
        self._match_count_r: dict[int, int] = {}
        self._pads_l: dict[int, tuple] = {}
        self._pads_r: dict[int, tuple] = {}

    # -- group assignment ---------------------------------------------------
    def _left_groups(self, jk, t) -> list:
        if self.matcher == "asof":
            return [jk]
        width = self.upper - self.lower
        b0 = int(np.floor((t + self.lower) / width))
        b1 = int(np.floor((t + self.upper) / width))
        return [(jk, b) for b in sorted({b0, b1})]

    def _right_groups(self, jk, t) -> list:
        if self.matcher == "asof":
            return [jk]
        width = self.upper - self.lower
        return [(jk, int(np.floor(t / width)))]

    # -- matchers ------------------------------------------------------------
    def _match_interval(self, lkeys: set, rkeys: set, group) -> list[tuple[int, int]]:
        out = []
        for lk in lkeys:
            _, tl = self.left.info[lk]
            for rk in rkeys:
                _, tr = self.right.info[rk]
                # pair discovered only in the group of tr's bucket (unique)
                if self.matcher == "interval" and self._right_groups(
                    self.right.info[rk][0], tr
                )[0] != group:
                    continue
                if self.lower <= tr - tl <= self.upper:
                    out.append((lk, rk))
        return out

    def _match_asof(self, lkeys: set, rkeys: set, group) -> list[tuple[int, int]]:
        rs = sorted(((self.right.info[rk][1], rk) for rk in rkeys))
        times = [t for t, _ in rs]
        out = []
        import bisect

        for lk in lkeys:
            _, tl = self.left.info[lk]
            pick = None
            if self.direction == "backward":
                pos = bisect.bisect_right(times, tl) - 1
                if pos >= 0:
                    pick = rs[pos][1]
            elif self.direction == "forward":
                pos = bisect.bisect_left(times, tl)
                if pos < len(rs):
                    pick = rs[pos][1]
            else:  # nearest
                pos = bisect.bisect_right(times, tl) - 1
                cands = []
                if pos >= 0:
                    cands.append(rs[pos])
                if pos + 1 < len(rs):
                    cands.append(rs[pos + 1])
                if cands:
                    pick = min(cands, key=lambda c: (abs(c[0] - tl), c[0]))[1]
            if pick is not None:
                out.append((lk, pick))
        return out

    # -- tick processing -----------------------------------------------------
    def _apply_delta(self, side: _Side, batch: DeltaBatch, is_left: bool, touched: set):
        jks = batch.data["__jk__"]
        ts = batch.data["__t__"]
        n_vals = self.n_left_cols if is_left else self.n_right_cols
        val_cols = [batch.data[f"__v{i}"] for i in range(n_vals)]
        group_of = self._left_groups if is_left else self._right_groups
        for i in range(len(batch)):
            k = int(batch.keys[i])
            if batch.diffs[i] > 0:
                side.rows[k] = tuple(c[i] for c in val_cols)
                side.info[k] = (jks[i], ts[i])
                for g in group_of(jks[i], ts[i]):
                    entry = self._groups.setdefault(g, (set(), set()))
                    (entry[0] if is_left else entry[1]).add(k)
                    touched.add(g)
            else:
                info = side.info.pop(k, None)
                side.rows.pop(k, None)
                if info is None:
                    continue
                for g in group_of(info[0], info[1]):
                    entry = self._groups.get(g)
                    if entry:
                        (entry[0] if is_left else entry[1]).discard(k)
                    touched.add(g)

    def process(self, inputs, time):
        touched: set = set()
        if inputs[0] is not None:
            self._apply_delta(self.left, inputs[0], True, touched)
        if inputs[1] is not None:
            self._apply_delta(self.right, inputs[1], False, touched)
        if not touched:
            return []

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []

        def emit(key, row, diff):
            out_keys.append(key)
            out_diffs.append(diff)
            out_rows.append(row)

        match = self._match_interval if self.matcher == "interval" else self._match_asof
        affected_l: set[int] = set()
        affected_r: set[int] = set()
        for g in touched:
            entry = self._groups.get(g, (set(), set()))
            new_pairs = {}
            for lk, rk in match(entry[0], entry[1], g):
                pid = _mix_pair(lk, rk)
                new_pairs[pid] = (lk, rk)
            old_ids = self._group_pairs.get(g, set())
            new_ids = set(new_pairs)
            for pid in old_ids - new_ids:
                row, lk, rk = self._pair_rows.pop(pid)
                emit(pid, row, -1)
                self._match_count_l[lk] -= 1
                self._match_count_r[rk] -= 1
                affected_l.add(lk)
                affected_r.add(rk)
            for pid in new_ids - old_ids:
                lk, rk = new_pairs[pid]
                row = (lk, rk) + self.left.rows[lk] + self.right.rows[rk]
                self._pair_rows[pid] = (row, lk, rk)
                emit(pid, row, +1)
                self._match_count_l[lk] = self._match_count_l.get(lk, 0) + 1
                self._match_count_r[rk] = self._match_count_r.get(rk, 0) + 1
                affected_l.add(lk)
                affected_r.add(rk)
            if new_ids:
                self._group_pairs[g] = new_ids
            else:
                self._group_pairs.pop(g, None)
            affected_l.update(entry[0])
            affected_r.update(entry[1])

        # outer padding reconciliation
        if self.how in ("left", "outer"):
            none_r = (None,) * self.n_right_cols
            for lk in affected_l:
                live = lk in self.left.rows
                want = live and self._match_count_l.get(lk, 0) == 0
                have = lk in self._pads_l
                if want and not have:
                    row = (lk, None) + self.left.rows[lk] + none_r
                    self._pads_l[lk] = row
                    emit(_pad_key(lk, 1), row, +1)
                elif have and not want:
                    emit(_pad_key(lk, 1), self._pads_l.pop(lk), -1)
        if self.how in ("right", "outer"):
            none_l = (None,) * self.n_left_cols
            for rk in affected_r:
                live = rk in self.right.rows
                want = live and self._match_count_r.get(rk, 0) == 0
                have = rk in self._pads_r
                if want and not have:
                    row = (None, rk) + none_l + self.right.rows[rk]
                    self._pads_r[rk] = row
                    emit(_pad_key(rk, 2), row, +1)
                elif have and not want:
                    emit(_pad_key(rk, 2), self._pads_r.pop(rk), -1)

        if not out_keys:
            return []
        names = self._out_names()
        return [DeltaBatch.from_rows(out_keys, out_rows, names, time, diffs=out_diffs)]

    def _out_names(self) -> list[str]:
        return (
            ["__left_id__", "__right_id__"]
            + [f"__lv{i}" for i in range(self.n_left_cols)]
            + [f"__rv{i}" for i in range(self.n_right_cols)]
        )


class AsofNowJoinNode(Node):
    """Append-only left (queries) joined against right state as of arrival."""

    name = "asof_now_join"

    snapshot_attrs = ("right", "_right_by_jk", "_answered")

    def exchange_key(self, port):
        # right state and query answering are both keyed by join key: shard by
        # __jk__ like TemporalJoinNode (queries meet exactly the right-state
        # shard they need; as-of-now answers are per-query-row)
        return lambda batch: batch.data["__jk__"].astype(np.uint64)

    def __init__(self, n_left_cols: int, n_right_cols: int, how: str):
        super().__init__(n_inputs=2)
        self.n_left_cols = n_left_cols
        self.n_right_cols = n_right_cols
        self.how = how
        self.right = _Side()
        self._right_by_jk: dict[Any, set[int]] = {}
        self._answered: dict[int, list[tuple[int, tuple]]] = {}  # lk -> emissions
        self._pending: list[DeltaBatch] = []  # queries awaiting the frontier

    def process(self, inputs, time):
        # right updates apply immediately; queries BUFFER until the frontier.
        # Under sharded sweeps a same-tick right update can arrive from
        # another worker after the query batch — answering at the frontier
        # (global quiescence) keeps "queries see every update of their tick"
        # deterministic regardless of sweep interleaving (the serial engine's
        # topo order gave this for free).
        if inputs[1] is not None:
            batch = inputs[1]
            jks = batch.data["__jk__"]
            val_cols = [batch.data[f"__v{i}"] for i in range(self.n_right_cols)]
            for i in range(len(batch)):
                k = int(batch.keys[i])
                if batch.diffs[i] > 0:
                    self.right.rows[k] = tuple(c[i] for c in val_cols)
                    self.right.info[k] = (jks[i], None)
                    self._right_by_jk.setdefault(jks[i], set()).add(k)
                else:
                    info = self.right.info.pop(k, None)
                    self.right.rows.pop(k, None)
                    if info is not None:
                        self._right_by_jk.get(info[0], set()).discard(k)
        if inputs[0] is not None:
            self._pending.append(inputs[0])
        return []

    def on_frontier(self, time):
        if not self._pending:
            return []
        batches, self._pending = self._pending, []
        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        for batch in batches:
            jks = batch.data["__jk__"]
            val_cols = [batch.data[f"__v{i}"] for i in range(self.n_left_cols)]
            for i in range(len(batch)):
                lk = int(batch.keys[i])
                if batch.diffs[i] > 0:
                    lrow = tuple(c[i] for c in val_cols)
                    matches = sorted(self._right_by_jk.get(jks[i], ()))
                    emits: list[tuple[int, tuple]] = []
                    if matches:
                        for rk in matches:
                            row = (lk, rk) + lrow + self.right.rows[rk]
                            emits.append((_mix_pair(lk, rk), row))
                    elif self.how == "left":
                        emits.append(
                            (_pad_key(lk, 1), (lk, None) + lrow + (None,) * self.n_right_cols)
                        )
                    for key, row in emits:
                        out_keys.append(key)
                        out_diffs.append(+1)
                        out_rows.append(row)
                    self._answered.setdefault(lk, []).extend(emits)
                else:
                    # query retracted (e.g. by upstream forget_immediately): retract
                    # exactly what it produced
                    for key, row in self._answered.pop(lk, []):
                        out_keys.append(key)
                        out_diffs.append(-1)
                        out_rows.append(row)
        if not out_keys:
            return []
        names = (
            ["__left_id__", "__right_id__"]
            + [f"__lv{i}" for i in range(self.n_left_cols)]
            + [f"__rv{i}" for i in range(self.n_right_cols)]
        )
        return [DeltaBatch.from_rows(out_keys, out_rows, names, time, diffs=out_diffs)]


# ----------------------------------------------------------------- result wrappers


class _TemporalJoinResult(JoinResult):
    """JoinResult whose materialization runs a temporal node instead of JoinNode."""

    def __init__(
        self,
        left: Table,
        right: Table,
        left_time,
        right_time,
        on: tuple,
        how: str,
        node_factory: Callable[[int, int, str], Node],
        behavior: CommonBehavior | None = None,
    ):
        super().__init__(left, right, on, how=how)
        self._lt = thisclass.bind_expression(expr_mod.wrap(left_time), left) if left_time is not None else None
        self._rt = thisclass.bind_expression(expr_mod.wrap(right_time), right) if right_time is not None else None
        self._node_factory = node_factory
        self._behavior = behavior
        self._defaults: dict = {}

    def _rewrite(self, e, joined):
        out = super()._rewrite(e, joined)
        if isinstance(e, expr_mod.ColumnReference) and self._defaults:
            for ref, val in self._defaults.items():
                if ref.table is e.table and ref.name == e.name:
                    from pathway_tpu.internals.expression import coalesce

                    # `out` already references the joined table — no re-rewrite
                    return coalesce(out, val)
        return out

    def _materialize(self) -> Table:
        if self._joined is not None:
            return self._joined
        left, right = self.left, self.right
        l_cols = left.column_names()
        r_cols = right.column_names()
        # no equality conditions → one global group (PointerExpression with no
        # args would degenerate to the row's own id)
        l_jk = expr_mod.PointerExpression(left, *self.left_on) if self.left_on else 0
        r_jk = expr_mod.PointerExpression(right, *self.right_on) if self.right_on else 0
        pre_l = left.select(
            **{f"__v{i}": left[n] for i, n in enumerate(l_cols)},
            __jk__=l_jk,
            __t__=self._lt if self._lt is not None else 0,
        )
        pre_r = right.select(
            **{f"__v{i}": right[n] for i, n in enumerate(r_cols)},
            __jk__=r_jk,
            __t__=self._rt if self._rt is not None else 0,
        )
        if self._behavior is not None:
            pre_l = apply_temporal_behavior(pre_l, self._behavior, "__t__")
            pre_r = apply_temporal_behavior(pre_r, self._behavior, "__t__")
        nl, nr = len(l_cols), len(r_cols)
        how = self.how
        factory = self._node_factory
        node = LogicalNode(
            lambda: factory(nl, nr), [pre_l._node, pre_r._node], name="temporal_join"
        )
        l_opt = how in ("right", "outer")
        r_opt = how in ("left", "outer")
        dtypes: dict[str, dt.DType] = {
            "__left_id__": dt.Optional(dt.POINTER) if l_opt else dt.POINTER,
            "__right_id__": dt.Optional(dt.POINTER) if r_opt else dt.POINTER,
        }
        renames: dict[str, str] = {}
        for i, n in enumerate(l_cols):
            d = left._schema.dtypes()[n]
            dtypes[f"__lv{i}"] = dt.Optional(d) if l_opt else d
            renames[f"__l__{n}"] = f"__lv{i}"
        for i, n in enumerate(r_cols):
            d = right._schema.dtypes()[n]
            dtypes[f"__rv{i}"] = dt.Optional(d) if r_opt else d
            renames[f"__r__{n}"] = f"__rv{i}"
        raw = Table(node, schema_mod.schema_from_dtypes(dtypes), Universe())
        # JoinResult._rewrite expects __l__<name>/__r__<name> columns
        sel = {"__left_id__": raw["__left_id__"], "__right_id__": raw["__right_id__"]}
        for pub, priv in renames.items():
            sel[pub] = raw[priv]
        self._joined = raw.select(**sel)
        return self._joined


def interval(lower_bound, upper_bound):
    """The interval of an interval join (reference ``temporal.interval``)."""
    return _Interval(lower_bound, upper_bound)


class _Interval:
    def __init__(self, lower_bound, upper_bound):
        if upper_bound <= lower_bound:
            raise ValueError("interval upper_bound must exceed lower_bound")
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound


def _interval_join_impl(left, right, left_time, right_time, iv, on, how, behavior=None):
    if not isinstance(iv, _Interval):
        raise ValueError("pass interval=pw.temporal.interval(lower, upper)")
    lo, up = iv.lower_bound, iv.upper_bound
    return _TemporalJoinResult(
        left, right, left_time, right_time, on, how,
        lambda nl, nr, h=how: TemporalJoinNode(
            nl, nr, h, matcher="interval", lower=lo, upper=up
        ),
        behavior=behavior,
    )


def interval_join(left, right, left_time, right_time, iv, *on, how="inner", behavior=None):
    """Rows pair when ``lower ≤ right_time − left_time ≤ upper`` (plus equality
    conditions). Reference ``_interval_join.py:577``."""
    return _interval_join_impl(left, right, left_time, right_time, iv, on, how, behavior)


def interval_join_inner(left, right, lt, rt, iv, *on, behavior=None):
    return _interval_join_impl(left, right, lt, rt, iv, on, "inner", behavior)


def interval_join_left(left, right, lt, rt, iv, *on, behavior=None):
    return _interval_join_impl(left, right, lt, rt, iv, on, "left", behavior)


def interval_join_right(left, right, lt, rt, iv, *on, behavior=None):
    return _interval_join_impl(left, right, lt, rt, iv, on, "right", behavior)


def interval_join_outer(left, right, lt, rt, iv, *on, behavior=None):
    return _interval_join_impl(left, right, lt, rt, iv, on, "outer", behavior)


class Direction:
    BACKWARD = "backward"
    FORWARD = "forward"
    NEAREST = "nearest"


def asof_join(
    left,
    right,
    left_time,
    right_time,
    *on,
    how="left",
    direction: str = "backward",
    behavior=None,
    defaults: dict | None = None,
):
    """Each left row matches the single right row closest in time per ``direction``
    (backward: latest right ≤ left). Reference ``_asof_join.py:479``."""
    direction = getattr(direction, "value", direction)
    res = _TemporalJoinResult(
        left, right, left_time, right_time, on, how,
        lambda nl, nr, h=how: TemporalJoinNode(
            nl, nr, h, matcher="asof", direction=direction
        ),
        behavior=behavior,
    )
    if defaults:
        res._defaults = dict(defaults)
    return res


def asof_join_left(left, right, lt, rt, *on, **kw):
    return asof_join(left, right, lt, rt, *on, how="left", **kw)


def asof_join_right(left, right, lt, rt, *on, **kw):
    return asof_join(left, right, lt, rt, *on, how="right", **kw)


def asof_join_outer(left, right, lt, rt, *on, **kw):
    return asof_join(left, right, lt, rt, *on, how="outer", **kw)


def asof_now_join(left, right, *on, how="inner", **kw):
    """Join where the left side is an append-only query stream answered against
    the right side's state at arrival; answers are never revised when the right
    side later changes (reference ``_asof_now_join.py``)."""
    if how not in ("inner", "left"):
        raise ValueError("asof_now_join supports how='inner' or 'left'")
    return _TemporalJoinResult(
        left, right, None, None, on, how,
        lambda nl, nr, h=how: AsofNowJoinNode(nl, nr, h),
    )


def asof_now_join_inner(left, right, *on, **kw):
    return asof_now_join(left, right, *on, how="inner", **kw)


def asof_now_join_left(left, right, *on, **kw):
    return asof_now_join(left, right, *on, how="left", **kw)
