"""Window join: pair rows whose times fall in the same window.

Reference ``stdlib/temporal/_window_join.py:156``: assign tumbling/sliding windows
to both sides' time columns, then equi-join on (window, *on). Built from the same
assignment program as ``windowby`` plus the standard hash join.
"""

from __future__ import annotations

from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.expression import ColumnExpression, ColumnReference
from pathway_tpu.stdlib.temporal._window import _SlidingWindow, _TumblingWindow


def _rebind(e, old_table, new_table):
    if isinstance(e, ColumnReference):
        if e.table is old_table:
            return new_table[e.name]
        return e
    args = e._args()
    if not args:
        return e
    return e._with_args(tuple(_rebind(a, old_table, new_table) for a in args))


def window_join(left, right, left_time, right_time, window, *on, how="inner"):
    import pathway_tpu as pw

    if isinstance(window, _TumblingWindow):
        hop, duration, origin = window.duration, window.duration, window.origin
    elif isinstance(window, _SlidingWindow):
        hop, duration, origin = window.hop, window.duration, window.origin
    else:
        raise ValueError("window_join supports tumbling/sliding windows")

    def assign(t):
        if t is None:
            return ()
        base = 0 if origin is None else origin
        last_k = int((t - base) // hop)
        first_k = last_k - int(duration // hop) - 1
        out = []
        for k in range(first_k, last_k + 2):
            start = base + k * hop
            if start <= t < start + duration and (origin is None or start >= origin):
                out.append((start, start + duration))
        return tuple(out)

    def widen(table, time_expr):
        t = table.with_columns(
            _pw_window=pw.apply_with_type(
                assign, dt.List(dt.Tuple(dt.ANY, dt.ANY)), table._bind(time_expr)
            )
        )
        t = t.flatten(t._pw_window)
        return t.with_columns(
            _pw_window_start=pw.this._pw_window.get(0),
            _pw_window_end=pw.this._pw_window.get(1),
        )

    lw = widen(left, left_time)
    rw = widen(right, right_time)
    conds = [lw._pw_window_start == rw._pw_window_start]
    for cond in on:
        if isinstance(cond, ColumnReference):
            conds.append(lw[cond.name] == rw[cond.name])
        else:
            conds.append(_rebind(_rebind(cond, left, lw), right, rw))
    return _WindowJoinResult(lw.join(rw, *conds, how=how), left, lw, right, rw)


class _WindowJoinResult:
    """Delegates to the widened-tables JoinResult, rebinding user expressions that
    reference the ORIGINAL tables onto the widened copies."""

    def __init__(self, inner, left, lw, right, rw):
        self._inner = inner
        self._pairs = [(left, lw), (right, rw)]

    def _map(self, e):
        for old, new in self._pairs:
            if hasattr(e, "_args") or isinstance(e, ColumnReference):
                e = _rebind(e, old, new)
        return e

    def select(self, *args, **kwargs):
        args = [self._map(a) if isinstance(a, ColumnExpression) else a for a in args]
        kwargs = {
            n: self._map(e) if isinstance(e, ColumnExpression) else e
            for n, e in kwargs.items()
        }
        return self._inner.select(*args, **kwargs)

    def filter(self, e):
        return _WindowJoinResult(
            self._inner.filter(self._map(e) if isinstance(e, ColumnExpression) else e),
            *[x for p in self._pairs for x in p],
        )

    def reduce(self, *args, **kwargs):
        args = [self._map(a) if isinstance(a, ColumnExpression) else a for a in args]
        kwargs = {
            n: self._map(e) if isinstance(e, ColumnExpression) else e
            for n, e in kwargs.items()
        }
        return self._inner.reduce(*args, **kwargs)


def window_join_inner(left, right, lt, rt, window, *on):
    return window_join(left, right, lt, rt, window, *on, how="inner")


def window_join_left(left, right, lt, rt, window, *on):
    return window_join(left, right, lt, rt, window, *on, how="left")


def window_join_right(left, right, lt, rt, window, *on):
    return window_join(left, right, lt, rt, window, *on, how="right")


def window_join_outer(left, right, lt, rt, window, *on):
    return window_join(left, right, lt, rt, window, *on, how="outer")
