"""Temporal operations (reference: ``python/pathway/stdlib/temporal/``).

Windows (tumbling/sliding/session/intervals_over), temporal behaviors
(delay/cutoff/exactly-once), interval joins, asof joins, as-of-now joins, and
window joins — see the submodules for engine notes.
"""

from pathway_tpu.stdlib.temporal.behaviors import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
    apply_temporal_behavior,
    common_behavior,
    exactly_once_behavior,
)
from pathway_tpu.stdlib.temporal._window import (
    Window,
    intervals_over,
    session,
    sliding,
    tumbling,
    windowby_impl,
)
from pathway_tpu.stdlib.temporal._temporal_join import (
    Direction,
    asof_join,
    asof_join_left,
    asof_join_outer,
    asof_join_right,
    asof_now_join,
    asof_now_join_inner,
    asof_now_join_left,
    interval,
    interval_join,
    interval_join_inner,
    interval_join_left,
    interval_join_outer,
    interval_join_right,
)
from pathway_tpu.stdlib.temporal._window_join import (
    window_join,
    window_join_inner,
    window_join_left,
    window_join_outer,
    window_join_right,
)

__all__ = [
    "Behavior",
    "CommonBehavior",
    "Direction",
    "ExactlyOnceBehavior",
    "Window",
    "apply_temporal_behavior",
    "asof_join",
    "asof_join_left",
    "asof_join_outer",
    "asof_join_right",
    "asof_now_join",
    "asof_now_join_inner",
    "asof_now_join_left",
    "common_behavior",
    "exactly_once_behavior",
    "interval",
    "interval_join",
    "interval_join_inner",
    "interval_join_left",
    "interval_join_outer",
    "interval_join_right",
    "intervals_over",
    "session",
    "sliding",
    "tumbling",
    "window_join",
    "window_join_inner",
    "window_join_left",
    "window_join_outer",
    "window_join_right",
    "windowby_impl",
]
