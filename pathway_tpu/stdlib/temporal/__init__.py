"""Temporal operations (reference: ``python/pathway/stdlib/temporal/``).

Windows, behaviors, interval/asof joins land in the temporal milestone; this module
keeps the import surface stable.
"""

def __getattr__(name):
    from pathway_tpu.stdlib.temporal import _impl
    if hasattr(_impl, name):
        return getattr(_impl, name)
    raise AttributeError(name)
