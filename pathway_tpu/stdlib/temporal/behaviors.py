"""Temporal behaviors: delay / cutoff / keep_results configuration.

API parity with the reference's ``stdlib/temporal/temporal_behavior.py:29,83``
(``common_behavior``, ``exactly_once_behavior``); the semantics ride the engine's
buffer/forget/freeze primitives (``pathway_tpu/internals/time_ops.py``):

- ``delay`` buffers entries until the operator's tracked time (max seen) passes
  ``entry time + delay`` — batching against too-frequent updates.
- ``cutoff`` stops updating results older than ``max seen time - cutoff``: late
  entries are dropped (freeze) and state is released (forget).
- ``keep_results=False`` additionally forgets already-emitted results past cutoff.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


class Behavior:
    """Base class of temporal behavior configs."""


@dataclass
class CommonBehavior(Behavior):
    delay: Any | None
    cutoff: Any | None
    keep_results: bool


def common_behavior(delay=None, cutoff=None, keep_results: bool = True) -> CommonBehavior:
    if cutoff is None and not keep_results:
        raise ValueError("keep_results=False requires a cutoff")
    return CommonBehavior(delay, cutoff, keep_results)


@dataclass
class ExactlyOnceBehavior(Behavior):
    shift: Any | None


def exactly_once_behavior(shift=None) -> ExactlyOnceBehavior:
    """Each non-empty window produces exactly one output, at ``window end + shift``."""
    return ExactlyOnceBehavior(shift)


def apply_temporal_behavior(table, behavior: CommonBehavior | None, time_column="_pw_time"):
    """Apply delay/cutoff to a table carrying its event time in ``time_column``
    (reference ``temporal_behavior.py:103-116``)."""
    import pathway_tpu as pw

    if behavior is None:
        return table
    t = table[time_column] if isinstance(time_column, str) else time_column
    if behavior.delay is not None:
        table = table._buffer(t + behavior.delay, t)
        t = table[time_column] if isinstance(time_column, str) else time_column
    if behavior.cutoff is not None:
        threshold = t + behavior.cutoff
        table = table._freeze(threshold, t)
        t = table[time_column] if isinstance(time_column, str) else time_column
        if not behavior.keep_results:
            table = table._forget(t + behavior.cutoff, t, behavior.keep_results)
    return table
