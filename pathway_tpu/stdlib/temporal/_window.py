"""Windows: tumbling, sliding, session, intervals_over + ``windowby``.

Behavior parity with the reference's ``stdlib/temporal/_window.py:588-855`` windows,
built TPU-engine-first: tumbling/sliding assignment is a vectorized rowwise program
(each row → list of ``(instance, start, end)`` tuples) followed by ``flatten`` and an
incremental ``groupby`` — all batch-oriented engine ops. Session windows, whose
assignment depends on neighboring rows, are a dedicated stateful engine node that
re-derives the touched instance's sessions per tick and emits row-level deltas.

After ``windowby(...).reduce(...)`` the grouping columns ``_pw_window``,
``_pw_instance``, ``_pw_window_start``, ``_pw_window_end`` are available, as in the
reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from pathway_tpu.engine.blocks import DeltaBatch
from pathway_tpu.engine.graph import Node
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals.logical import LogicalNode
from pathway_tpu.stdlib.temporal.behaviors import (
    Behavior,
    CommonBehavior,
    ExactlyOnceBehavior,
)


class Window:
    def _apply(self, table, key, behavior, instance):
        raise NotImplementedError


@dataclass
class _TumblingWindow(Window):
    duration: Any
    origin: Any = None

    def _apply(self, table, key, behavior, instance):
        return _apply_fixed_window(
            table, key, behavior, instance,
            hop=self.duration, duration=self.duration, origin=self.origin,
        )


@dataclass
class _SlidingWindow(Window):
    hop: Any
    duration: Any
    origin: Any = None

    def _apply(self, table, key, behavior, instance):
        return _apply_fixed_window(
            table, key, behavior, instance,
            hop=self.hop, duration=self.duration, origin=self.origin,
        )


@dataclass
class _SessionWindow(Window):
    predicate: Callable | None = None
    max_gap: Any = None

    def _apply(self, table, key, behavior, instance):
        return _apply_session_window(table, key, behavior, instance, self)


@dataclass
class _IntervalsOverWindow(Window):
    at: Any  # ColumnReference into the query-points table
    lower_bound: Any
    upper_bound: Any
    is_outer: bool = True


def tumbling(duration, origin=None) -> Window:
    """Non-overlapping fixed windows of ``duration`` starting at ``origin + k*duration``
    (reference ``_window.py`` tumbling)."""
    return _TumblingWindow(duration, origin)


def sliding(hop, duration=None, ratio=None, origin=None) -> Window:
    """Overlapping windows of ``duration`` (or ``hop*ratio``) every ``hop``."""
    if (duration is None) == (ratio is None):
        raise ValueError("provide exactly one of duration / ratio")
    return _SlidingWindow(hop, duration if duration is not None else hop * ratio, origin)


def session(*, predicate=None, max_gap=None) -> Window:
    """Group adjacent entries: ``predicate(a, b)`` or ``b - a < max_gap``."""
    if (predicate is None) == (max_gap is None):
        raise ValueError("provide exactly one of predicate / max_gap")
    return _SessionWindow(predicate, max_gap)


def intervals_over(*, at, lower_bound, upper_bound, is_outer: bool = True) -> Window:
    """For each time point in ``at``, a window ``[t+lower_bound, t+upper_bound]``
    gathering the data rows inside (powers ``statistical.interpolate``)."""
    return _IntervalsOverWindow(at, lower_bound, upper_bound, is_outer)


# ------------------------------------------------------------------ tumbling/sliding


def _apply_fixed_window(table, key, behavior, instance, *, hop, duration, origin):
    import pathway_tpu as pw

    origin_val = origin

    def assign(inst, t):
        if t is None:
            return ()
        base = 0 if origin_val is None else origin_val
        last_k = int((t - base) // hop)
        first_k = last_k - int(duration // hop) - 1
        out = []
        for k in range(first_k, last_k + 2):
            start = base + k * hop
            end = start + duration
            if start <= t < end and (origin_val is None or start >= origin_val):
                out.append((inst, start, end))
        return tuple(out)

    target = table.with_columns(
        _pw_window=pw.apply_with_type(
            assign,
            dt.List(dt.Tuple(dt.ANY, dt.ANY, dt.ANY)),
            instance,
            key,
        ),
        _pw_key=key,
    )
    target = target.flatten(target._pw_window)
    target = target.with_columns(
        _pw_instance=pw.this._pw_window.get(0),
        _pw_window_start=pw.this._pw_window.get(1),
        _pw_window_end=pw.this._pw_window.get(2),
    )
    target = _apply_window_behavior(target, behavior)
    return _window_groupby(target)


def _apply_window_behavior(target, behavior):
    import pathway_tpu as pw

    if behavior is None:
        return target
    if isinstance(behavior, ExactlyOnceBehavior):
        # exactly-once: hold everything until window end + shift, then freeze
        shift = behavior.shift if behavior.shift is not None else 0
        target = target._buffer(
            pw.this._pw_window_end + shift, pw.this._pw_key
        )
        target = target._freeze(
            pw.this._pw_window_end + shift, pw.this._pw_key
        )
        return target
    if not isinstance(behavior, CommonBehavior):
        raise ValueError(f"behavior {behavior!r} unsupported for this window")
    if behavior.cutoff is not None:
        target = target._freeze(
            pw.this._pw_window_end + behavior.cutoff, pw.this._pw_key
        )
    if behavior.delay is not None:
        target = target._buffer(
            pw.this._pw_window_start + behavior.delay, pw.this._pw_key
        )
    if behavior.cutoff is not None and not behavior.keep_results:
        # keep_results=True in the reference forgets upstream state but filters the
        # forgetting retractions out of the output (results stay); here state stays
        # and results stay — same observable behavior, memory release deferred
        target = target._forget(
            pw.this._pw_window_end + behavior.cutoff,
            pw.this._pw_key,
            behavior.keep_results,
        )
    return target


def _window_groupby(target):
    grouped = target.groupby(
        target._pw_window,
        target._pw_instance,
        target._pw_window_start,
        target._pw_window_end,
    )
    return grouped


# ------------------------------------------------------------------ session windows


class SessionAssignNode(Node):
    """Stateful session assignment: per instance, sort rows by time and merge
    adjacent entries per predicate/max_gap; emit rows + (start, end) deltas."""

    name = "session_assign"

    def exchange_key(self, port):
        # session state is independent per instance: shard by instance hash
        # (the reference keys its session arrangement the same way,
        # time_column.rs) — one instance's rows always co-locate, so sharded
        # runs are byte-identical to serial
        from pathway_tpu.internals.keys import hash_column

        return lambda batch: hash_column(batch.data["__inst"])

    def __init__(self, columns: list[str], predicate, max_gap):
        super().__init__(n_inputs=1)
        self.columns = columns  # input column names (incl. __t/__inst materialized)
        self.predicate = predicate
        self.max_gap = max_gap
        self._rows: dict[int, tuple] = {}  # key -> row values
        self._info: dict[int, tuple[Any, Any]] = {}  # key -> (inst, t)
        self._by_instance: dict[Any, set[int]] = {}
        self._emitted: dict[int, tuple] = {}  # key -> emitted (row + start + end)

    def _grouped(self, a, b) -> bool:
        if self.predicate is not None:
            return bool(self.predicate(a, b))
        return bool(b - a < self.max_gap)

    def process(self, inputs, time):
        batch = inputs[0]
        if batch is None:
            return []
        t_col = batch.data["__t"]
        inst_col = batch.data["__inst"]
        cols = [batch.data[n] for n in self.columns]
        touched: set = set()
        for i in range(len(batch)):
            k = int(batch.keys[i])
            if batch.diffs[i] > 0:
                self._rows[k] = tuple(c[i] for c in cols)
                self._info[k] = (inst_col[i], t_col[i])
                self._by_instance.setdefault(inst_col[i], set()).add(k)
                touched.add(inst_col[i])
            else:
                info = self._info.pop(k, None)
                self._rows.pop(k, None)
                if info is not None:
                    self._by_instance.get(info[0], set()).discard(k)
                    touched.add(info[0])

        out_keys: list[int] = []
        out_diffs: list[int] = []
        out_rows: list[tuple] = []
        for inst in touched:
            members = sorted(
                self._by_instance.get(inst, ()), key=lambda k: (self._info[k][1], k)
            )
            # walk in time order, splitting where adjacent rows don't group
            sessions: list[list[int]] = []
            for k in members:
                if sessions and self._grouped(
                    self._info[sessions[-1][-1]][1], self._info[k][1]
                ):
                    sessions[-1].append(k)
                else:
                    sessions.append([k])
            assigned: dict[int, tuple] = {}
            for sess in sessions:
                start = self._info[sess[0]][1]
                end = self._info[sess[-1]][1]
                for k in sess:
                    assigned[k] = (start, end)
            for k, (start, end) in assigned.items():
                new_row = self._rows[k] + ((inst, start, end), inst, start, end)
                old = self._emitted.get(k)
                if old == new_row:
                    continue
                if old is not None:
                    out_keys.append(k)
                    out_diffs.append(-1)
                    out_rows.append(old)
                out_keys.append(k)
                out_diffs.append(+1)
                out_rows.append(new_row)
                self._emitted[k] = new_row
        # retract emissions of deleted rows
        for i in range(len(batch)):
            k = int(batch.keys[i])
            if batch.diffs[i] < 0 and k not in self._rows:
                old = self._emitted.pop(k, None)
                if old is not None:
                    out_keys.append(k)
                    out_diffs.append(-1)
                    out_rows.append(old)
        if not out_keys:
            return []
        names = self.columns + ["_pw_window", "_pw_instance", "_pw_window_start", "_pw_window_end"]
        return [DeltaBatch.from_rows(out_keys, out_rows, names, time, diffs=out_diffs)]


def _apply_session_window(table, key, behavior, instance, window: _SessionWindow):
    from pathway_tpu.internals import schema as schema_mod
    from pathway_tpu.internals.table import Table

    base_cols = table.column_names()
    pre = table.with_columns(__t=key, __inst=instance if instance is not None else 0)
    col_names = pre.column_names()
    node = LogicalNode(
        lambda: SessionAssignNode(col_names, window.predicate, window.max_gap),
        [pre._node],
        name="session_window",
    )
    dtypes = dict(pre._schema.dtypes())
    dtypes["_pw_window"] = dt.Tuple(dt.ANY, dt.ANY, dt.ANY)
    dtypes["_pw_instance"] = dt.ANY
    dtypes["_pw_window_start"] = dtypes["__t"]
    dtypes["_pw_window_end"] = dtypes["__t"]
    from pathway_tpu.internals.universe import Universe

    assigned = Table(node, schema_mod.schema_from_dtypes(dtypes), Universe())
    if behavior is not None:
        assigned = assigned.with_columns(_pw_key=assigned["__t"])
        assigned = _apply_window_behavior(assigned, behavior)
    return _window_groupby(assigned)


# ------------------------------------------------------------------ intervals_over


def _apply_intervals_over(table, key, behavior, window: _IntervalsOverWindow):
    """Each query point ``p`` (from ``window.at``) gathers data rows with
    ``key ∈ [p+lower, p+upper]``: bucketed equi-join + filter + groupby."""
    import pathway_tpu as pw
    from pathway_tpu.internals.expression import ColumnReference

    at_ref = window.at
    if not isinstance(at_ref, ColumnReference):
        raise ValueError("intervals_over needs at=<column reference>")
    points = at_ref.table.select(__p=at_ref)
    lo, up = window.lower_bound, window.upper_bound
    width = up - lo
    if width <= 0:
        raise ValueError("intervals_over requires upper_bound > lower_bound")

    def point_buckets(p):
        b0 = int(np.floor((p + lo) / width))
        b1 = int(np.floor((p + up) / width))
        return tuple(sorted({b0, b1}))

    pts = points.with_columns(
        __b=pw.apply_with_type(point_buckets, dt.List(dt.INT), pw.this["__p"])
    )
    pts = pts.flatten(pts["__b"], origin_id="__point_id")

    def row_bucket(t):
        return int(np.floor(t / width))

    data = table.with_columns(
        __t=key, __b=pw.apply_with_type(row_bucket, dt.INT, key)
    )
    jr = pts.join(data, pts["__b"] == data["__b"], how="inner").filter(
        (pw.right["__t"] >= pw.left["__p"] + lo)
        & (pw.right["__t"] <= pw.left["__p"] + up)
    )
    sel = {}
    for n in table.column_names():
        sel[n] = pw.right[n]
    window_cols = dict(
        _pw_window=pw.apply_with_type(
            lambda p: (None, p + lo, p + up),
            dt.Tuple(dt.ANY, dt.ANY, dt.ANY),
            pw.left["__p"],
        ),
        _pw_instance=pw.declare_type(dt.ANY, None),
        _pw_window_location=pw.left["__p"],
        _pw_window_start=pw.left["__p"] + lo,
        _pw_window_end=pw.left["__p"] + up,
    )
    joined = jr.select(__point_id=pw.left["__point_id"], **window_cols, **sel)
    if window.is_outer:
        # points whose window matched nothing still produce a (padded) window:
        # antijoin via groupby keyed by the point id, then set-difference
        matched = joined.groupby(
            joined["__point_id"], id=joined["__point_id"]
        ).reduce(__c=pw.reducers.count())
        unmatched = points.difference(matched)
        pads = unmatched.select(
            __point_id=pw.this.id,
            _pw_window=pw.apply_with_type(
                lambda p: (None, p + lo, p + up),
                dt.Tuple(dt.ANY, dt.ANY, dt.ANY),
                pw.this["__p"],
            ),
            _pw_instance=pw.declare_type(dt.ANY, None),
            _pw_window_location=pw.this["__p"],
            _pw_window_start=pw.this["__p"] + lo,
            _pw_window_end=pw.this["__p"] + up,
            **{n: pw.declare_type(dt.Optional(table._schema.dtypes()[n]), None) for n in table.column_names()},
        )
        joined = joined.concat_reindex(pads)
    grouped = joined.groupby(
        joined._pw_window,
        joined._pw_instance,
        joined._pw_window_location,
        joined._pw_window_start,
        joined._pw_window_end,
    )
    return grouped


def windowby_impl(table, time_expr, *, window: Window, instance=None, behavior=None, **kwargs):
    key = table._bind(time_expr)
    inst = table._bind(instance) if instance is not None else None
    if isinstance(window, _IntervalsOverWindow):
        if behavior is not None:
            raise NotImplementedError(
                "behavior is not yet supported for intervals_over windows"
            )
        return _apply_intervals_over(table, key, behavior, window)
    return window._apply(table, key, behavior, inst)
