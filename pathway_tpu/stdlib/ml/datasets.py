"""Dataset download helpers (reference: ``stdlib/ml/datasets/``). This image
has no network egress, so fetching is dependency-gated; local files load."""

from __future__ import annotations

import os


def load_lsh_test_data(path: str | None = None):
    if path and os.path.exists(path):
        import numpy as np

        return np.load(path)
    raise NotImplementedError(
        "dataset download requires network access; pass a local path instead"
    )
