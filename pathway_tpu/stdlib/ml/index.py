"""Legacy ``KNNIndex`` wrapper (reference: ``stdlib/ml/index.py``) — the
pre-DataIndex API over the LSH classifier: construct with embeddings, query
with ``get_nearest_items`` in collapsed (tuple columns per query) or flat
(row per match) form."""

from __future__ import annotations

from typing import Any

import pathway_tpu as pw
from pathway_tpu.stdlib.ml.classifiers import knn_lsh_classifier_train


class KNNIndex:
    """LSH-bucketed KNN over a data table's embedding column."""

    def __init__(
        self,
        data_embedding: "pw.ColumnExpression",
        data: "pw.Table",
        n_dimensions: int,
        n_or: int = 20,
        n_and: int = 10,
        bucket_length: float = 10.0,
        distance_type: str = "euclidean",
        metadata: Any = None,
    ):
        if metadata is not None:
            raise NotImplementedError(
                "KNNIndex metadata filtering is not supported; use "
                "stdlib.indexing.DataIndex (JMESPath filters) instead"
            )
        self.data = data
        embeddings = data.select(data=data_embedding)
        self._query = knn_lsh_classifier_train(
            embeddings,
            L=n_or,
            d=n_dimensions,
            M=n_and,
            A=bucket_length,
            type=distance_type,
        )

    def get_nearest_items(
        self,
        query_embedding: "pw.ColumnReference",
        k: int = 3,
        collapse_rows: bool = True,
        with_distances: bool = False,
        metadata_filter: Any = None,
    ) -> "pw.Table":
        if metadata_filter is not None:
            raise NotImplementedError(
                "KNNIndex metadata_filter is not supported; use "
                "stdlib.indexing.DataIndex (JMESPath filters) instead"
            )
        qtable = query_embedding.table
        queries = qtable.select(data=query_embedding)
        knns = self._query(queries, k)
        data = self.data
        data_cols = data.column_names()

        if collapse_rows:
            # one row per query; each data column becomes a tuple of matches
            paired = knns.select(
                pairs=pw.apply(
                    lambda ids, ds: tuple(zip(ids, ds)), knns.knns_ids, knns.knns_dists
                )
            )
            flat = paired.flatten(paired.pairs, origin_id="query_id")
            parts = flat.select(
                query_id=flat.query_id,
                doc=pw.apply(lambda p: p[0], flat.pairs),
                dist=pw.apply(lambda p: p[1], flat.pairs),
            )
            gathered = parts.select(
                query_id=parts.query_id,
                dist=parts.dist,
                **{c: data.ix(parts.doc)[c] for c in data_cols},
            )
            agg = {c: pw.reducers.tuple(gathered[c]) for c in data_cols}
            agg["dist"] = pw.reducers.tuple(gathered.dist)
            grouped = gathered.groupby(gathered.query_id).reduce(
                query_id=gathered.query_id, **agg
            )
            rekeyed = grouped.with_id(grouped.query_id)
            out_cols = list(data_cols) + (["dist"] if with_distances else [])

            def sort_by_dist(dist, *cols):
                order = sorted(range(len(dist)), key=lambda i: dist[i])
                return tuple(
                    tuple(c[i] for i in order) for c in (cols + (dist,))
                )

            packed = rekeyed.select(
                p=pw.apply(sort_by_dist, rekeyed.dist, *[rekeyed[c] for c in data_cols])
            )
            sel = {
                c: pw.apply(lambda p, j=j: p[j], packed.p)
                for j, c in enumerate(data_cols)
            }
            if with_distances:
                sel["dist"] = pw.apply(lambda p: p[-1], packed.p)
            out = packed.select(**sel)
            # queries with no matches still get a row of empty tuples
            empty = knns.select(**{c: () for c in out_cols})
            return empty.update_rows(out)

        paired = knns.select(
            pairs=pw.apply(
                lambda ids, ds: tuple(zip(ids, ds)), knns.knns_ids, knns.knns_dists
            )
        )
        flat = paired.flatten(paired.pairs, origin_id="query_id")
        parts = flat.select(
            query_id=flat.query_id,
            doc=pw.apply(lambda p: p[0], flat.pairs),
            dist=pw.apply(lambda p: p[1], flat.pairs),
        )
        extra = {"dist": parts.dist} if with_distances else {}
        return parts.select(
            query_id=parts.query_id,
            **{c: data.ix(parts.doc)[c] for c in data_cols},
            **extra,
        )

    def get_nearest_items_asof_now(self, query_embedding, **kwargs) -> "pw.Table":
        """Answers are computed as of each query's arrival; in this engine the
        LSH query path already answers against the index state at query time."""
        return self.get_nearest_items(query_embedding, **kwargs)
