"""Hidden-Markov-Model decoding reducer (reference: ``stdlib/ml/hmm.py``).

``create_hmm_reducer(graph)`` returns a ``pw.reducers.udf_reducer``-style
reducer running ONLINE Viterbi: each appended observation advances the
log-probability front one transition (optionally beam-trimmed) and the
accumulator's result is the most likely hidden-state path so far.

Graph contract (same as the reference): a ``networkx.DiGraph`` whose nodes
carry ``calc_emission_log_ppb(observation) -> float``, whose edges carry
``log_transition_ppb``, and whose graph dict names ``start_nodes``.
"""

from __future__ import annotations

from collections import deque

import numpy as np

import pathway_tpu as pw


def create_hmm_reducer(graph, beam_size: int | None = None, num_results_kept: int | None = None):
    nodes = list(graph.nodes)
    idx_of = {node: i for i, node in enumerate(nodes)}
    n = len(nodes)
    emit = [graph.nodes[node]["calc_emission_log_ppb"] for node in nodes]
    succs = [
        [
            (idx_of[t], graph.get_edge_data(node, t)["log_transition_ppb"])
            for t in graph.successors(node)
        ]
        for node in nodes
    ]
    start_idx = [idx_of[s] for s in graph.graph["start_nodes"]]
    beam = beam_size if beam_size is not None else n + 1

    class HmmAccumulator(pw.BaseCustomAccumulator):
        def __init__(self, observation):
            self._obs = observation
            self.ppb = np.full(n, -np.inf)
            for i in start_idx:
                self.ppb[i] = emit[i](observation)
            self.live = list(start_idx)
            # num_results_kept bounds state, not just the returned path —
            # an unbounded stream would otherwise grow O(T * n) per key
            self.backpointers: deque[np.ndarray] = deque(maxlen=num_results_kept)
            self._trim()

        @classmethod
        def from_row(cls, row):
            [observation] = row
            return cls(observation)

        def _trim(self) -> None:
            if len(self.live) > beam:
                costs = self.ppb[self.live]
                keep = np.argsort(costs)[-beam:]
                kept = {self.live[int(i)] for i in keep}
                for i in self.live:
                    if i not in kept:
                        self.ppb[i] = -np.inf
                self.live = sorted(kept)

        def update(self, other) -> None:
            # other is a freshly-seeded accumulator for ONE observation; its
            # start distribution is ignored — we advance OUR front with its
            # observation (append-only online decoding, like the reference)
            observation = other._obs
            new_ppb = np.full(n, -np.inf)
            back = np.full(n, -1, dtype=np.int64)
            for i in self.live:
                base = self.ppb[i]
                for j, log_t in succs[i]:
                    cand = base + log_t
                    if cand > new_ppb[j] or (cand == new_ppb[j] and i < back[j]):
                        new_ppb[j] = cand
                        back[j] = i
            live = [j for j in range(n) if np.isfinite(new_ppb[j])]
            for j in live:
                new_ppb[j] += emit[j](observation)
            self.ppb = new_ppb
            self.live = live
            self.backpointers.append(back)
            self._trim()

        def compute_result(self):
            if not self.live:
                return ()
            cur = int(max(self.live, key=lambda j: (self.ppb[j], -j)))
            path = [nodes[cur]]
            for back in reversed(self.backpointers):
                cur = int(back[cur])
                if cur < 0:
                    break
                path.append(nodes[cur])
            path.reverse()
            if num_results_kept is not None:
                path = path[-num_results_kept:]
            return tuple(path)

    return pw.reducers.udf_reducer(HmmAccumulator)
