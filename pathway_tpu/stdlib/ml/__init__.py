"""ML stdlib (reference: ``python/pathway/stdlib/ml/``): LSH KNN classifiers.
The dense TPU-native KNN index lives in ``pathway_tpu.ops.knn`` /
``stdlib.indexing`` — classifiers here are the sub-linear LSH pruning path."""

from pathway_tpu.stdlib.ml import classifiers

__all__ = ["classifiers"]
