"""ML stdlib (reference: ``python/pathway/stdlib/ml/``): LSH KNN classifiers,
the legacy KNNIndex wrapper, fuzzy joins, HMM decoding. The dense TPU-native
KNN index lives in ``pathway_tpu.ops.knn`` / ``stdlib.indexing``."""

from pathway_tpu.stdlib.ml import classifiers, smart_table_ops
from pathway_tpu.stdlib.ml.index import KNNIndex

__all__ = ["KNNIndex", "classifiers", "smart_table_ops"]


def __getattr__(name):  # hmm pulls networkx; import lazily
    if name == "hmm":
        from pathway_tpu.stdlib.ml import hmm

        return hmm
    if name == "datasets":
        from pathway_tpu.stdlib.ml import datasets

        return datasets
    raise AttributeError(name)
