"""Fuzzy joins (reference: ``stdlib/ml/smart_table_ops/``): match rows of two
tables by shared text features, weighted by rarity, keeping mutually-best
pairs.

Own-design pipeline (the reference iterates heavy/light hitters over a
normalizer matrix; this build reaches the same contract with the engine's
vectorized primitives): tokenize each row's columns into word features →
weight each feature by ``1 / log(1 + global count)`` (LOGWEIGHT) or
``1 / count`` (WEIGHT) → candidate pairs via an equi-join on the feature →
score = sum of shared feature weights → keep pairs that are the best match
for BOTH sides (mutual argmax, deterministic tie-breaks), optionally seeded /
overridden by a ``by_hand_match`` table.
"""

from __future__ import annotations

import re
from typing import Any

import pathway_tpu as pw


class FuzzyJoinNormalization:
    WEIGHT = "weight"
    LOGWEIGHT = "logweight"
    NONE = "none"


class FuzzyJoinFeatureGeneration:
    AUTO = "auto"
    WORDS = "words"


class JoinResult(pw.Schema):
    left: Any
    right: Any
    weight: float


def _featurize(table: "pw.Table") -> "pw.Table":
    cols = table.column_names()

    def words(*vals):
        toks: list[str] = []
        for v in vals:
            if v is None:
                continue
            toks.extend(re.findall(r"[a-z0-9]+", str(v).lower()))
        return tuple(sorted(set(toks)))

    feats = table.select(
        feats=pw.apply(words, *[table[c] for c in cols])
    )
    flat = feats.flatten(feats.feats, origin_id="row")
    return flat.select(row=flat.row, feature=flat.feats)


def _weighted(features: "pw.Table", normalization: str) -> "pw.Table":
    counts = features.groupby(features.feature).reduce(
        feature=features.feature, n=pw.reducers.count()
    )

    if normalization == FuzzyJoinNormalization.WEIGHT:
        def w(n):
            return 1.0 / n
    elif normalization == FuzzyJoinNormalization.LOGWEIGHT:
        import math

        def w(n):
            return 1.0 / math.log(1.0 + n)
    else:
        def w(n):
            return 1.0

    return counts.select(feature=counts.feature, weight=pw.apply(w, counts.n))


def fuzzy_match(
    left_features: "pw.Table",
    right_features: "pw.Table",
    normalization: str = FuzzyJoinNormalization.LOGWEIGHT,
    _exclude_same_row: bool = False,
) -> "pw.Table":
    """Match by precomputed (row, feature) tables; returns JoinResult rows."""
    all_feats = pw.Table.concat_reindex(
        left_features.select(feature=left_features.feature),
        right_features.select(feature=right_features.feature),
    )
    weights = _weighted(all_feats, normalization)

    lw = left_features.join(weights, left_features.feature == weights.feature).select(
        row=left_features.row, feature=left_features.feature, weight=weights.weight
    )
    pairs = lw.join(right_features, lw.feature == right_features.feature).select(
        left=lw.row, right=right_features.row, weight=lw.weight
    )
    scored = pairs.groupby(pairs.left, pairs.right).reduce(
        left=pairs.left, right=pairs.right, weight=pw.reducers.sum(pairs.weight)
    )
    if _exclude_same_row:
        # self-match: the trivial identity pair would always win the argmax
        scored = scored.filter(
            pw.apply(lambda l, r: int(l) != int(r), scored.left, scored.right)
        )

    # mutual best: each side keeps its argmax partner; negated key in the
    # packed tuple makes max() break weight ties toward the SMALLER key
    packed = scored.select(
        left=scored.left,
        right=scored.right,
        weight=scored.weight,
        wr=pw.apply(lambda w, r: (w, -int(r)), scored.weight, scored.right),
        wl=pw.apply(lambda w, l: (w, -int(l)), scored.weight, scored.left),
    )
    best_r = packed.groupby(packed.left).reduce(
        left=packed.left, best=pw.reducers.max(packed.wr)
    )
    best_l = packed.groupby(packed.right).reduce(
        right=packed.right, best=pw.reducers.max(packed.wl)
    )
    joined = (
        packed.join(best_r, packed.left == best_r.left)
        .select(
            left=packed.left,
            right=packed.right,
            weight=packed.weight,
            wr=packed.wr,
            wl=packed.wl,
            best_r=best_r.best,
        )
    )
    joined = joined.join(best_l, joined.right == best_l.right).select(
        left=joined.left,
        right=joined.right,
        weight=joined.weight,
        keep=pw.apply(
            lambda wr, br, wl, bl: wr == br and wl == bl,
            joined.wr,
            joined.best_r,
            joined.wl,
            best_l.best,
        ),
    )
    return joined.filter(joined.keep).select(
        left=joined.left, right=joined.right, weight=joined.weight
    )


def fuzzy_match_tables(
    left_table: "pw.Table",
    right_table: "pw.Table",
    *,
    by_hand_match: "pw.Table" = None,
    normalization: str = FuzzyJoinNormalization.LOGWEIGHT,
    feature_generation: str = FuzzyJoinFeatureGeneration.AUTO,
    left_projection: dict | None = None,
    right_projection: dict | None = None,
) -> "pw.Table":
    """Match rows of two tables by fuzzy text similarity over all columns
    (or the projected subsets)."""
    lt = left_table
    rt = right_table
    if left_projection:
        lt = left_table.select(**{c: left_table[c] for c in left_projection})
    if right_projection:
        rt = right_table.select(**{c: right_table[c] for c in right_projection})
    result = fuzzy_match(_featurize(lt), _featurize(rt), normalization)
    if by_hand_match is not None:
        forced = by_hand_match.select(
            left=by_hand_match.left,
            right=by_hand_match.right,
            weight=by_hand_match.weight,
        )
        # forced pairs replace any computed pair for the same left row
        keep = result.join_left(forced, result.left == forced.left).select(
            left=result.left,
            right=result.right,
            weight=result.weight,
            overridden=forced.right.is_not_none(),
        )
        surviving = keep.filter(~keep.overridden).select(
            left=keep.left, right=keep.right, weight=keep.weight
        )
        result = pw.Table.concat_reindex(surviving, forced)
    return result


def fuzzy_self_match(
    table: "pw.Table",
    normalization: str = FuzzyJoinNormalization.LOGWEIGHT,
) -> "pw.Table":
    """Match rows of a table against itself (excluding the trivial self-pair)."""
    feats = _featurize(table)
    return fuzzy_match(feats, feats, normalization, _exclude_same_row=True)
