"""LSH bucketers (reference: ``stdlib/ml/classifiers/_lsh.py``).

A bucketer maps a vector to ``L`` band ids; vectors sharing a band id are
candidate neighbors. Euclidean uses random-projection quantization (p-stable
LSH), cosine uses random-hyperplane signs. Band hashing is vectorized over the
whole batch of vectors — one matmul + one quantization per call — so the hot
loop is a single BLAS/XLA-friendly contraction rather than per-row hashing.
"""

from __future__ import annotations

import numpy as np


def _band_ids(codes: np.ndarray, L: int, M: int) -> np.ndarray:
    """codes: (n, L*M) int array → (n, L) stable band hashes."""
    n = codes.shape[0]
    bands = codes.reshape(n, L, M)
    # polynomial rolling hash per band, vectorized
    h = np.zeros((n, L), dtype=np.uint64)
    mult = np.uint64(0x100000001B3)
    with np.errstate(over="ignore"):
        for j in range(M):
            h = h * mult ^ (bands[:, :, j].astype(np.uint64) + np.uint64(0x9E3779B9))
        # salt each band position so identical codes in different bands differ
        h = h * mult ^ np.arange(L, dtype=np.uint64)[None, :]
    return h


def generate_euclidean_lsh_bucketer(
    d: int, M: int = 10, L: int = 5, A: float = 1.0, seed: int = 0
):
    """p-stable (Gaussian projection) LSH for euclidean distance: code
    ``floor((x·r + b) / A)`` per projection, ``M`` projections per band."""
    rng = np.random.default_rng(seed)
    R = rng.normal(size=(d, M * L))
    B = rng.uniform(0, A, size=(M * L,))

    def bucketer(vectors: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(vectors, dtype=np.float64))
        codes = np.floor((x @ R + B) / A).astype(np.int64)
        return _band_ids(codes, L, M)

    bucketer.L = L
    bucketer.d = d
    return bucketer


def generate_cosine_lsh_bucketer(d: int, M: int = 10, L: int = 5, seed: int = 0):
    """Random-hyperplane LSH for cosine distance: code = sign(x·r)."""
    rng = np.random.default_rng(seed)
    R = rng.normal(size=(d, M * L))

    def bucketer(vectors: np.ndarray) -> np.ndarray:
        x = np.atleast_2d(np.asarray(vectors, dtype=np.float64))
        codes = (x @ R > 0).astype(np.int64)
        return _band_ids(codes, L, M)

    bucketer.L = L
    bucketer.d = d
    return bucketer
