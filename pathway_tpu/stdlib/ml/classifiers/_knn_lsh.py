"""LSH-bucketed KNN classifier (reference: ``stdlib/ml/classifiers/_knn_lsh.py``).

Dataflow shape: training vectors flatten into (band, bucket) rows; queries
bucket the same way and equi-join on the band hash, giving per-query candidate
sets that stay incremental under training-data updates. Each query row's
candidate set resolves with ONE (n_candidates, d) distance kernel (the dense
brute-force TPU path lives in ``ops/knn.py``; LSH is the sub-linear candidate
pruner for huge training sets).
"""

from __future__ import annotations

from collections import Counter

import numpy as np

import pathway_tpu as pw

from ._lsh import generate_cosine_lsh_bucketer, generate_euclidean_lsh_bucketer


class DataPoint(pw.Schema):
    data: np.ndarray


def _euclidean_dist_sq(data: np.ndarray, query: np.ndarray) -> np.ndarray:
    diff = data - query[None, :]
    return (diff * diff).sum(axis=1)


def _cosine_dist(data: np.ndarray, query: np.ndarray) -> np.ndarray:
    qn = np.linalg.norm(query) or 1.0
    dn = np.linalg.norm(data, axis=1)
    dn[dn == 0] = 1.0
    return 1.0 - (data @ query) / (dn * qn)


def knn_lsh_generic_classifier_train(data: pw.Table, bucketer, distance=_euclidean_dist_sq):
    """``data``: rows with ``data`` (vector). Returns a model whose
    ``query(queries, k)`` yields per-query candidate KNN ids."""

    def band_rows(table: pw.Table) -> pw.Table:
        banded = table.select(
            origin=table.id,
            bands=pw.apply(lambda v: tuple(int(b) for b in bucketer(v)[0]), table.data),
        )
        flat = banded.flatten(banded.bands, origin_id="row")
        return flat.select(
            origin=flat.row,
            band=flat.bands,
        )

    index = band_rows(data)

    def query_fn(queries: pw.Table, k: int) -> pw.Table:
        qbands = band_rows(queries)
        raw_hits = qbands.join(index, qbands.band == index.band).select(
            query=qbands.origin, candidate=index.origin
        )
        # multi-band matches produce duplicate (query, candidate) pairs
        hits = raw_hits.groupby(raw_hits.query, raw_hits.candidate).reduce(
            query=raw_hits.query, candidate=raw_hits.candidate
        )

        gathered = hits.select(
            query=hits.query,
            candidate=hits.candidate,
            qv=queries.ix(hits.query).data,
            cv=data.ix(hits.candidate).data,
        )
        grouped = gathered.groupby(gathered.query).reduce(
            query=gathered.query,
            qv=pw.reducers.any(gathered.qv),
            cands=pw.reducers.tuple(gathered.candidate),
            vecs=pw.reducers.tuple(gathered.cv),
        )

        def topk(qv, cands, vecs):
            # ONE (n_candidates, d) distance kernel per query row; ties break
            # by candidate id so results are worker-layout independent
            mat = np.stack([np.asarray(v, dtype=np.float64) for v in vecs])
            dists = distance(mat, np.asarray(qv, dtype=np.float64))
            order = np.lexsort((np.asarray(cands, dtype=np.uint64), dists))[:k]
            return (
                tuple(cands[i] for i in order),
                tuple(float(dists[i]) for i in order),
            )

        rekeyed = grouped.with_id(grouped.query)
        pair = rekeyed.select(
            p=pw.apply(topk, rekeyed.qv, rekeyed.cands, rekeyed.vecs)
        )
        knns = pair.select(
            knns_ids=pw.apply(lambda p: p[0], pair.p),
            knns_dists=pw.apply(lambda p: p[1], pair.p),
        )
        # queries with zero candidates still get a row (empty tuples)
        return queries.select(knns_ids=(), knns_dists=()).update_rows(knns)

    return query_fn


def knn_lsh_classifier_train(
    data: pw.Table,
    L: int = 5,
    type: str = "euclidean",  # noqa: A002 — reference-parity name
    **kwargs,
):
    """Dispatch on metric (reference ``knn_lsh_classifier_train``). kwargs:
    ``d`` (dimension, required), ``M`` (projections per band), ``A``
    (euclidean quantization width), ``seed``."""
    d = kwargs.pop("d")
    M = kwargs.pop("M", 10)
    if type == "euclidean":
        A = kwargs.pop("A", 1.0)
        bucketer = generate_euclidean_lsh_bucketer(d, M=M, L=L, A=A, **kwargs)
        return knn_lsh_generic_classifier_train(data, bucketer, _euclidean_dist_sq)
    if type == "cosine":
        bucketer = generate_cosine_lsh_bucketer(d, M=M, L=L, **kwargs)
        return knn_lsh_generic_classifier_train(data, bucketer, _cosine_dist)
    raise ValueError(f"unknown lsh metric {type!r}")


def knn_lsh_euclidean_classifier_train(data: pw.Table, d: int, M: int, L: int, A: float):
    bucketer = generate_euclidean_lsh_bucketer(d, M=M, L=L, A=A)
    return knn_lsh_generic_classifier_train(data, bucketer, _euclidean_dist_sq)


def knn_lsh_classify(knn_model, data_labels: pw.Table, queries: pw.Table, k: int) -> pw.Table:
    """Majority-vote labels of each query's k nearest training rows."""
    knns = knn_model(queries, k)
    flat = knns.flatten(knns.knns_ids, origin_id="q")
    flat = flat.select(q=flat.q, label=data_labels.ix(flat.knns_ids).label)
    votes = flat.groupby(flat.q).reduce(
        q=flat.q, labels=pw.reducers.tuple(flat.label)
    )

    def majority(labels):
        if not labels:
            return None
        return Counter(labels).most_common(1)[0][0]

    rekeyed = votes.with_id(votes.q)
    predicted = rekeyed.select(predicted_label=pw.apply(majority, rekeyed.labels))
    none_rows = knns.select(predicted_label=None)
    return none_rows.update_rows(predicted)
