"""ML classifiers (reference: ``python/pathway/stdlib/ml/classifiers/``)."""

from pathway_tpu.stdlib.ml.classifiers._knn_lsh import (
    DataPoint,
    knn_lsh_classifier_train,
    knn_lsh_classify,
    knn_lsh_euclidean_classifier_train,
    knn_lsh_generic_classifier_train,
)
from pathway_tpu.stdlib.ml.classifiers._lsh import (
    generate_cosine_lsh_bucketer,
    generate_euclidean_lsh_bucketer,
)

__all__ = [
    "DataPoint",
    "knn_lsh_classifier_train",
    "knn_lsh_classify",
    "knn_lsh_euclidean_classifier_train",
    "knn_lsh_generic_classifier_train",
    "generate_cosine_lsh_bucketer",
    "generate_euclidean_lsh_bucketer",
]
