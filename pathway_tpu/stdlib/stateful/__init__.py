"""Stateful stdlib (reference: ``python/pathway/stdlib/stateful/``)."""

from __future__ import annotations

from typing import Callable

import pathway_tpu as pw


def deduplicate(
    table: "pw.Table",
    *,
    col=None,
    instance=None,
    acceptor: Callable | None = None,
    value=None,
) -> "pw.Table":
    """Keep, per ``instance``, the latest row whose ``col`` value the
    ``acceptor(new_value, previous_accepted)`` callback accepts
    (reference: ``stdlib/stateful/deduplicate.py``)."""
    if col is not None and value is not None:
        raise ValueError("deduplicate: pass either col= or value=, not both")
    return table.deduplicate(
        value=col if col is not None else value, instance=instance, acceptor=acceptor
    )


__all__ = ["deduplicate"]
