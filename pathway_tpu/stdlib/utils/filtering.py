"""Row-filtering helpers (role of the reference's
``python/pathway/stdlib/utils/filtering.py``: keep, per group, only the row where
``what`` is extreme).

Implementation here: a single extremal reduce drives ``ix`` lookups back into the
source table — the winner row is re-materialized by pointer rather than by
restricting the original universe, so the result's ids are the *group* ids (stable
under winner churn), and no subset promise is needed.
"""

from __future__ import annotations

import pathway_tpu as pw


def _extremal_rows(table: pw.Table, on, what, reducer) -> pw.Table:
    champions = table.groupby(*on).reduce(winner=reducer(what))
    return champions.select(
        **{name: table.ix(champions.winner)[name] for name in table.column_names()}
    )


def argmax_rows(table: pw.Table, *on: pw.ColumnReference, what) -> pw.Table:
    """One row per group of ``on``: the row maximizing ``what``."""
    return _extremal_rows(table, on, what, pw.reducers.argmax)


def argmin_rows(table: pw.Table, *on: pw.ColumnReference, what) -> pw.Table:
    """One row per group of ``on``: the row minimizing ``what``."""
    return _extremal_rows(table, on, what, pw.reducers.argmin)
