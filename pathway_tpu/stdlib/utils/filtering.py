"""Row-filtering helpers (reference: ``python/pathway/stdlib/utils/filtering.py``).

``argmax_rows``/``argmin_rows`` keep, per group, the single row where ``what`` is
extreme — implemented as an argmax/argmin reduce whose winning row id re-keys a
restriction of the original table.
"""

from __future__ import annotations

import pathway_tpu as pw


def argmax_rows(table: pw.Table, *on: pw.ColumnReference, what) -> pw.Table:
    winners = (
        table.groupby(*on)
        .reduce(argmax_id=pw.reducers.argmax(what))
        .with_id(pw.this.argmax_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(winners, strict=False)


def argmin_rows(table: pw.Table, *on: pw.ColumnReference, what) -> pw.Table:
    winners = (
        table.groupby(*on)
        .reduce(argmin_id=pw.reducers.argmin(what))
        .with_id(pw.this.argmin_id)
        .promise_universe_is_subset_of(table)
    )
    return table.restrict(winners, strict=False)
