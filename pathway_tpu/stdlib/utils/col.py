"""Column utilities (reference: ``python/pathway/stdlib/utils/col.py``:
``unpack_col``, ``apply_all_rows``, ``multiapply_all_rows``,
``groupby_reduce_majority``)."""

from __future__ import annotations

from collections import Counter
from typing import Callable, Sequence

import pathway_tpu as pw


def unpack_col(column, *unpacked_columns, schema=None):
    """Expand a tuple-valued column into one column per element.

    ``unpacked_columns`` are output names (or column refs whose names are
    used); alternatively pass ``schema`` to name+type the outputs.
    """
    if schema is not None and unpacked_columns:
        raise ValueError("unpack_col: pass either unpacked_columns or schema, not both")
    if schema is not None:
        names = schema.column_names()
    else:
        names = [c if isinstance(c, str) else c.name for c in unpacked_columns]
    table = column.table
    kwargs = {
        name: pw.apply(lambda t, i=i: t[i], column) for i, name in enumerate(names)
    }
    return table.select(**kwargs)


def apply_all_rows(
    *cols, fun: Callable[..., Sequence], result_col_name: str
) -> "pw.Table":
    """Apply ``fun`` to entire columns at once (lists, one per column); the
    returned list maps back onto the original rows."""
    return multiapply_all_rows(
        *cols, fun=lambda *cs: (fun(*cs),), result_col_names=[result_col_name]
    )


def multiapply_all_rows(
    *cols, fun: Callable[..., Sequence[Sequence]], result_col_names: list
) -> "pw.Table":
    """Like ``apply_all_rows`` but ``fun`` returns several output columns."""
    assert cols, "multiapply_all_rows needs at least one column"
    table = cols[0].table
    names = [c if isinstance(c, str) else c.name for c in result_col_names]

    tmp = table.select(id_and_cols=pw.apply(lambda i, *vs: (i, *vs), table.id, *cols))
    reduced = tmp.reduce(ids_and_cols=pw.reducers.sorted_tuple(tmp.id_and_cols))

    def fun_wrapped(ids_and_cols):
        ids, *col_lists = zip(*ids_and_cols)
        res = fun(*col_lists)
        return tuple(zip(ids, *res))

    applied = reduced.select(ids_and_res=pw.apply(fun_wrapped, reduced.ids_and_cols))
    flat = applied.flatten(applied.ids_and_res)
    unpacked = unpack_col(flat.ids_and_res, "idd", *names)
    rekeyed = unpacked.with_id(unpacked.idd)
    out = rekeyed.select(**{n: rekeyed[n] for n in names})
    return out.with_universe_of(table)


def groupby_reduce_majority(column_group, column_val):
    """Per group: the most frequent value of ``column_val``
    (reference ``col.py:309``)."""
    table = column_group.table
    pairs = table.groupby(column_group).reduce(
        group=column_group, vals=pw.reducers.tuple(column_val)
    )
    return pairs.select(
        group=pairs.group,
        majority=pw.apply(lambda vs: Counter(vs).most_common(1)[0][0], pairs.vals),
    )
