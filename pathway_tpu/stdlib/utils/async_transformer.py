"""``pw.AsyncTransformer`` — async row transforms looped back into the dataflow.

Counterpart of the reference's async-transformer pair
(``python/pathway/stdlib/utils/async_transformer.py:370`` +
``src/engine/dataflow/async_transformer.rs``): each insertion of the input
table schedules ``invoke(**row)`` on a dedicated asyncio loop thread; each
completion is pushed — keyed by the ORIGINAL row id — into an upsert stream
source that re-enters the graph, so results arrive at later logical times
without ever blocking a tick. Deletions/updates of input rows retract or
replace their result rows (upsert session semantics).

Output surface matches the reference: ``output_table`` /
``finished`` (adds ``_async_status`` = "-SUCCESS-" | "-FAILURE-"),
``successful`` (status filtered out), ``failed``.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any

import pathway_tpu as pw
from pathway_tpu.internals import dtype as dt
from pathway_tpu.internals import schema as schema_mod

_ASYNC_STATUS_COLUMN = "_async_status"
_SUCCESS = "-SUCCESS-"
_FAILURE = "-FAILURE-"


class _ResultSubject(pw.io.python.ConnectorSubject):
    """Bridge from the asyncio loop back into the engine: results arrive via
    direct keyed pushes; ``run`` just waits for the transformer to drain."""

    def __init__(self, owner: "AsyncTransformer"):
        super().__init__()
        self.owner = owner
        self._done = threading.Event()

    @property
    def _session_type(self) -> str:
        return "upsert"

    def run(self) -> None:
        self._done.wait()

    def push_result(self, key: int, values: tuple | None) -> None:
        if self._node is not None:
            self._node.push(key, values, 1 if values is not None else -1)

    def finish(self) -> None:
        self._done.set()


class _AsyncDriver:
    """Connector driver: keeps the run alive while invocations are in flight.
    Finishes only after two consecutive idle checks (one-tick hysteresis), so
    results dispatched during the final drain tick still get ingested."""

    virtual = False

    def __init__(self, owner: "AsyncTransformer"):
        self.owner = owner
        self.subject = owner._subject
        self._prev_snapshot: tuple[int, int] | None = None

    def start(self) -> None:
        self.owner._start_loop()

    def is_finished(self) -> bool:
        o = self.owner
        snapshot = (o._dispatched, o._completed)
        idle = (
            o._dispatched == o._completed
            and snapshot == self._prev_snapshot
            and o._subject._node is not None
            and not o._subject._node._pending
        )
        self._prev_snapshot = snapshot
        if idle:
            self.subject.finish()
        return idle

    def stop(self) -> None:
        self.subject.finish()
        o = self.owner
        if o._loop is not None:
            o._loop.call_soon_threadsafe(lambda: None)


class AsyncTransformer:
    """Subclass with ``output_schema`` and an ``async def invoke(self, **row)``
    returning a dict matching the schema."""

    output_schema: Any = None

    def __init_subclass__(cls, /, output_schema=None, **kwargs):
        super().__init_subclass__(**kwargs)
        if output_schema is not None:
            cls.output_schema = output_schema

    def __init__(
        self,
        input_table: "pw.Table",
        *,
        instance: Any = None,
        autocommit_duration_ms: int | None = None,
    ):
        if self.output_schema is None:
            raise TypeError(
                "AsyncTransformer subclass needs output_schema "
                "(class Mine(pw.AsyncTransformer, output_schema=...))"
            )
        self._input_table = input_table
        self._loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._dispatched = 0
        self._completed = 0
        self._subject = _ResultSubject(self)

        out_cols = dict(self.output_schema.dtypes())
        out_cols[_ASYNC_STATUS_COLUMN] = dt.STR
        self._result_schema = schema_mod.schema_from_dtypes(out_cols)

        # the result source table (upsert by input-row key)
        self._output = pw.io.python.read(
            self._subject, schema=self._result_schema, name="async_transformer"
        )
        # register the driver + input subscription
        self._install()

    # -- user API -----------------------------------------------------------
    async def invoke(self, **kwargs) -> dict:
        raise NotImplementedError

    def open(self) -> None:
        """Called once on the loop thread before the first invoke."""

    def close(self) -> None:
        """Called once after the stream ends."""

    @property
    def output_table(self) -> "pw.Table":
        return self._output

    @property
    def finished(self) -> "pw.Table":
        return self._output

    @property
    def successful(self) -> "pw.Table":
        t = self._output
        ok = t.filter(t[_ASYNC_STATUS_COLUMN] == _SUCCESS)
        names = self.output_schema.column_names()
        return ok.select(**{n: ok[n] for n in names})

    @property
    def failed(self) -> "pw.Table":
        t = self._output
        bad = t.filter(t[_ASYNC_STATUS_COLUMN] == _FAILURE)
        names = self.output_schema.column_names()
        return bad.select(**{n: bad[n] for n in names})

    def with_options(self, **kwargs) -> "AsyncTransformer":
        if kwargs:
            import warnings

            warnings.warn(
                "AsyncTransformer.with_options: "
                f"{sorted(kwargs)} are not implemented yet and have NO effect "
                "(no retries, no capacity limit, no caching)",
                stacklevel=2,
            )
        return self

    # -- internals ----------------------------------------------------------
    def _start_loop(self) -> None:
        if self._loop_thread is not None:
            return
        ready = threading.Event()

        def loop_main() -> None:
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self.open()
            ready.set()
            self._loop.run_forever()

        self._loop_thread = threading.Thread(target=loop_main, daemon=True)
        self._loop_thread.start()
        ready.wait()

    def _install(self) -> None:
        in_cols = self._input_table._schema.column_names()
        out_names = self.output_schema.column_names()

        def on_change(key, row, time, is_addition):
            if not is_addition:
                # retraction: upsert session drops the result row
                self._subject.push_result(int(key), None)
                return
            self._start_loop()
            self._dispatched += 1

            async def task(key=int(key), row=dict(row)):
                try:
                    result = await self.invoke(**row)
                    values = tuple(result.get(n) for n in out_names) + (_SUCCESS,)
                except Exception as e:
                    import traceback

                    from pathway_tpu.internals.error_log import log_error

                    log_error(
                        -1,
                        f"AsyncTransformer.invoke failed: {e!r}",
                        traceback.format_exc(),
                    )
                    values = tuple(None for _ in out_names) + (_FAILURE,)
                self._subject.push_result(key, values)
                self._completed += 1

            asyncio.run_coroutine_threadsafe(task(), self._loop)

        pw.io.subscribe(
            self._input_table, on_change=on_change, on_end=self._on_input_end
        )
        # the driver that holds the run open is registered by read(); add ours
        # for lifecycle: piggyback on the result subject's driver via hook
        from pathway_tpu.internals.logical import LogicalNode

        output_lnode = self._output._node
        orig_hook = output_lnode.runtime_hook

        def hook(node, runtime):
            if orig_hook is not None:
                orig_hook(node, runtime)
            if runtime is not None:
                # replace the subject's thread driver with the async driver
                runtime.connectors[-1] = _AsyncDriver(self)

        output_lnode.runtime_hook = hook

    def _on_input_end(self) -> None:
        try:
            self.close()
        finally:
            pass
