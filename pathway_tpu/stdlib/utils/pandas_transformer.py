"""``@pw.pandas_transformer`` (reference: ``stdlib/utils/pandas_transformer.py``)
— run a pandas function over whole tables, re-entering the dataflow as a table.

The function receives each input table as a ``pandas.DataFrame`` (indexed by
row key) and returns a DataFrame; the output table is keyed by the returned
index. Like the reference, this materializes the full table per update — meant
for small control-plane tables, not the hot path."""

from __future__ import annotations

from typing import Any, Callable

import pathway_tpu as pw


def pandas_transformer(
    output_schema: Any, output_universe: Any = None
) -> Callable:
    """Decorator: ``fn(*dataframes) -> DataFrame`` becomes
    ``fn(*tables) -> Table`` with ``output_schema``."""
    if output_universe is not None:
        raise NotImplementedError(
            "pandas_transformer: output_universe pinning is not supported yet "
            "(the output is keyed by the returned DataFrame index)"
        )

    def wrapper(fn: Callable) -> Callable:
        def transformer(*tables: "pw.Table") -> "pw.Table":
            import pandas as pd

            packed = []
            for t in tables:
                cols = t.column_names()
                tmp = t.select(
                    packed=pw.apply(lambda i, *vs: (i, *vs), t.id, *[t[c] for c in cols])
                )
                packed.append(
                    (cols, tmp.reduce(rows=pw.reducers.sorted_tuple(tmp.packed)))
                )
            if len(packed) > 1:
                raise NotImplementedError(
                    "pandas_transformer over multiple tables is not supported yet"
                )
            cols, reduced = packed[0]
            out_cols = output_schema.column_names()

            def run(rows):
                idx = [r[0] for r in rows]
                df = pd.DataFrame(
                    {c: [r[1 + j] for r in rows] for j, c in enumerate(cols)},
                    index=idx,
                )
                result = fn(df)
                return tuple(
                    (int(i),) + tuple(result.iloc[pos][c] for c in out_cols)
                    for pos, i in enumerate(result.index)
                )

            applied = reduced.select(out=pw.apply(run, reduced.rows))
            flat = applied.flatten(applied.out)
            unpacked = flat.select(
                idd=pw.apply(lambda r: r[0], flat.out),
                **{
                    c: pw.apply(lambda r, j=j: r[1 + j], flat.out)
                    for j, c in enumerate(out_cols)
                },
            )
            rekeyed = unpacked.with_id(unpacked.idd)
            return rekeyed.select(**{c: rekeyed[c] for c in out_cols}).update_types(
                **output_schema.typehints()
            )

        return transformer

    return wrapper
