"""Utility stdlib (reference: ``python/pathway/stdlib/utils/``)."""

from pathway_tpu.stdlib.utils import bucketing, col, filtering
from pathway_tpu.stdlib.utils.async_transformer import AsyncTransformer
from pathway_tpu.stdlib.utils.col import (
    apply_all_rows,
    groupby_reduce_majority,
    multiapply_all_rows,
    unpack_col,
)
from pathway_tpu.stdlib.utils.filtering import argmax_rows, argmin_rows
from pathway_tpu.stdlib.utils.pandas_transformer import pandas_transformer

__all__ = [
    "AsyncTransformer",
    "apply_all_rows",
    "argmax_rows",
    "argmin_rows",
    "bucketing",
    "col",
    "filtering",
    "groupby_reduce_majority",
    "multiapply_all_rows",
    "pandas_transformer",
    "unpack_col",
]
