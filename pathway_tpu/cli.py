"""``pathway_tpu`` command line — spawn / spawn-from-env / replay.

Role of the reference CLI (``python/pathway/cli.py:53-113,167,253``): ``spawn``
forks N processes of a user program with the ``PATHWAY_THREADS / PATHWAY_PROCESSES /
PATHWAY_PROCESS_ID / PATHWAY_FIRST_PORT`` env contract consumed by
``parallel/cluster.py``; ``replay`` re-runs a program against a recorded
persistence log. Usage::

    python -m pathway_tpu spawn --threads 2 --processes 2 python script.py
    python -m pathway_tpu replay --record-path ./rec --mode speedrun python script.py
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys

import click

from pathway_tpu.internals.config import get_pathway_config


def _spawn_processes(env_base: dict[str, str], processes: int, args: tuple[str, ...]) -> int:
    """Fork one subprocess per process id; forward SIGINT/SIGTERM; return the
    first non-zero exit code (killing the rest), else 0."""
    if not args:
        raise click.UsageError("no program given (e.g. `spawn -t 2 python script.py`)")
    procs: list[subprocess.Popen] = []
    for pid in range(processes):
        env = dict(env_base, PATHWAY_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(list(args), env=env))

    def forward(signum, frame):
        for p in procs:
            if p.poll() is None:
                p.send_signal(signum)

    old_int = signal.signal(signal.SIGINT, forward)
    old_term = signal.signal(signal.SIGTERM, forward)
    try:
        code = 0
        for p in procs:
            rc = p.wait()
            if rc != 0 and code == 0:
                code = rc
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
        return code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


@click.group()
def cli() -> None:
    """pathway_tpu — TPU-native streaming dataflow framework."""


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1, help="workers per process")
@click.option("-n", "--processes", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, default=None, help="base TCP port for the cluster plane")
@click.option("--record", is_flag=True, default=False, help="record inputs for later replay")
@click.option("--record-path", type=str, default="./record", help="where recorded inputs live")
@click.option(
    "--trace",
    type=click.Choice(["off", "on"]),
    default=None,
    help="live span pipeline (PATHWAY_TRACE)",
)
@click.option(
    "--trace-sample", type=float, default=None, help="tick head-sampling rate (0, 1]"
)
@click.option(
    "--trace-file",
    type=str,
    default=None,
    help="rotating OTLP-JSON live sink (per-process .pN suffix)",
)
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn(threads, processes, first_port, record, record_path, trace, trace_sample, trace_file, program):
    """Run PROGRAM across THREADS×PROCESSES workers on this host."""
    import uuid

    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(threads)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_FIRST_PORT"] = str(
        first_port if first_port is not None else get_pathway_config().first_port
    )
    # one run id per launch: every process derives the SAME trace id from it,
    # so per-process tick spans (live + offline exports) stitch into one trace
    env.setdefault("PATHWAY_RUN_ID", uuid.uuid4().hex)
    if trace is not None:
        env["PATHWAY_TRACE"] = trace
    if trace_sample is not None:
        env["PATHWAY_TRACE_SAMPLE"] = str(trace_sample)
    if trace_file is not None:
        env["PATHWAY_TRACE_LIVE_FILE"] = trace_file
    if record:
        env["PATHWAY_PERSISTENT_STORAGE"] = record_path
        env["PATHWAY_RECORD"] = "1"
    sys.exit(_spawn_processes(env, processes, program))


@cli.command(context_settings={"ignore_unknown_options": True})
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn_from_env(program):
    """Like spawn, but topology comes from the current PATHWAY_* environment."""
    import uuid

    cfg = get_pathway_config()
    env = cfg.spawn_env(0)
    env.setdefault("PATHWAY_RUN_ID", uuid.uuid4().hex)  # shared trace id
    sys.exit(_spawn_processes(env, cfg.processes, program))


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=None, help="workers per process")
@click.option("-n", "--processes", type=int, default=None, help="number of processes")
@click.option("--first-port", type=int, default=None, help="base TCP port for the cluster plane")
@click.option("--max-restarts", type=int, default=None, help="restart budget on failure")
@click.option("--backoff", type=float, default=None, help="base restart backoff seconds")
@click.option("--log-dir", type=str, default=None, help="capture child output per attempt")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def supervise(threads, processes, first_port, max_restarts, backoff, log_dir, program):
    """Run PROGRAM under the resilience Supervisor: spawn the cluster, detect
    a failed process, relaunch from the last committed checkpoint epoch with
    bounded exponential backoff (``resilience.Supervisor``)."""
    from pathway_tpu.resilience import Supervisor, SupervisorGaveUp

    if not program:
        raise click.UsageError("no program given (e.g. `supervise -n 2 python script.py`)")
    try:
        result = Supervisor(
            list(program),
            threads=threads,
            processes=processes,
            first_port=first_port,
            max_restarts=max_restarts,
            backoff_s=backoff,
            log_dir=log_dir,
        ).run()
    except SupervisorGaveUp as e:
        raise click.ClickException(str(e)) from e
    if result.restarts:
        click.echo(f"pathway_tpu supervise: recovered after {result.restarts} restart(s)")
    sys.exit(0)


@cli.command()
@click.option("--to", "target", type=int, required=True, help="target process count")
@click.option(
    "--storage",
    type=str,
    default=None,
    help="persistence root holding the cluster's membership table "
    "(default PATHWAY_PERSISTENT_STORAGE)",
)
@click.option("--host", type=str, default=None, help="instead of the storage path, hit a RUNNING coordinator's monitoring server")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
def scale(target, storage, host, port):
    """Request a live rescale of a running elastic cluster to TARGET
    processes (``PATHWAY_ELASTIC=manual`` or ``auto``). Default transport is
    the persistence backend: the request lands in ``elastic/scale_request``
    and the coordinator adopts it on its next tick-continuation barrier; the
    pod then quiesces to one final committed checkpoint epoch and its
    Supervisor relaunches it at the new shape, state resharded by key range.
    With ``--host``, the request goes to the coordinator's monitoring server
    ``/scale`` endpoint instead (no filesystem access needed)."""
    if target < 1:
        raise click.UsageError(f"--to must be >= 1, got {target}")
    if host is not None:
        import json as _json
        import urllib.request

        if port is None:
            port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
        url = f"http://{host}:{port}/scale?to={target}"
        try:
            body = urllib.request.urlopen(url, timeout=5).read().decode()
        except OSError as e:
            raise click.ClickException(
                f"cannot reach monitoring server at {host}:{port}: {e} "
                "(is the pipeline running with with_http_server=True?)"
            ) from e
        doc = _json.loads(body)
        click.echo(_json.dumps(doc, indent=2))
        if doc.get("ok") is False:
            raise click.ClickException(doc.get("error", "scale request failed"))
        return
    storage = storage or get_pathway_config().persistent_storage
    if not storage:
        raise click.UsageError(
            "no persistence root: pass --storage or set PATHWAY_PERSISTENT_STORAGE "
            "(or use --host to reach a running coordinator)"
        )
    from pathway_tpu.elastic import write_scale_request
    from pathway_tpu.persistence.backends import FileBackend

    req = write_scale_request(FileBackend(storage), target, source="cli")
    click.echo(
        f"scale request to {target} process(es) recorded at {storage!r} "
        f"(requested_unix {req['requested_unix']:.3f}); the coordinator "
        "adopts it within a tick"
    )


@cli.command()
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
@click.option("--ticks", type=int, default=None, help="capture window length in ticks")
@click.option(
    "--dir",
    "out_dir",
    type=str,
    default=None,
    help="capture output directory (default PATHWAY_PROFILE_DIR of the target run)",
)
@click.option("--status", is_flag=True, default=False, help="report the current window instead of arming one")
def profile(host, port, ticks, out_dir, status):
    """Arm a live ``jax.profiler`` capture window on a RUNNING pipeline via
    its monitoring server's ``/profile`` endpoint (view the result in
    TensorBoard/XProf)."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    qs = {}
    if not status:
        qs["ticks"] = str(ticks if ticks is not None else get_pathway_config().profile_ticks)
        if out_dir:
            qs["dir"] = out_dir
    url = f"http://{host}:{port}/profile"
    if qs:
        url += "?" + urllib.parse.urlencode(qs)
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "profile request failed"))


@cli.command()
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
@click.option("--sink", type=str, default=None, help="sink label, e.g. subscribe:7 (omit to list sinks)")
@click.option("--key", type=str, default=None, help="output row key (decimal or 0x-hex)")
def explain(host, port, sink, key):
    """Ask a RUNNING pipeline why a sink row exists: walk the operator graph
    backward through the lineage rings (``/explain`` endpoint) and print the
    contributing input rows, operator path, and originating trace span ids.
    Requires ``PATHWAY_AUDIT`` on (the default) and ``with_http_server=True``."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    qs = {}
    if sink:
        qs["sink"] = sink
    if key is not None:
        qs["key"] = key
    url = f"http://{host}:{port}/explain"
    if qs:
        url += "?" + urllib.parse.urlencode(qs)
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "explain request failed"))


@cli.command()
@click.argument("request_id", required=False)
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
def trace(request_id, host, port):
    """Print one request's end-to-end flight path: per-stage latency
    decomposition + OTLP spans, from the request-trace plane's kept ring
    (``/request`` endpoint). Omit REQUEST_ID to list kept trace ids and the
    in-flight request table. The id is the ``X-Pathway-Request-Id`` response
    header the REST front door stamps on every admitted request."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    url = f"http://{host}:{port}/request"
    if request_id:
        url += "?" + urllib.parse.urlencode({"id": request_id})
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "trace request failed"))


def _fetch_json(host: str, port: int, path: str) -> dict:
    import json as _json
    import urllib.request

    url = f"http://{host}:{port}{path}"
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    return _json.loads(body)


def _spark(values: list, width: int = 24) -> str:
    """One-line unicode sparkline of a numeric series (newest right)."""
    blocks = "▁▂▃▄▅▆▇█"
    vals = [v for v in values if isinstance(v, (int, float))][-width:]
    if not vals:
        return "-" * 4
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(blocks[int((v - lo) / span * (len(blocks) - 1))] for v in vals)


def render_top(status: dict, tl: dict) -> str:
    """One ``pathway_tpu top`` frame from a /status + /timeline pair (pure —
    the live loop and the tests share it)."""

    def series(metric: str) -> list:
        return [p.get(metric) for p in tl.get("points") or () if p.get(metric) is not None]

    def last(metric: str, default=0):
        s = series(metric)
        return s[-1] if s else default

    lines = []
    procs = tl.get("procs") or []
    lines.append(
        f"pathway_tpu top — proc {tl.get('proc')} of {len(procs) or 1} "
        f"reporting ({', '.join(procs)})"
    )
    lines.append(
        f"  qps {last('serve_qps'):>8.1f} {_spark(series('serve_qps'))}   "
        f"tick_rate {last('tick_rate'):>7.1f} {_spark(series('tick_rate'))}"
    )
    lines.append(
        f"  backlog {last('backlog_rows'):>6} {_spark(series('backlog_rows'))}   "
        f"wm_lag_s {last('watermark_lag_s', 0.0):>7.2f} "
        f"{_spark(series('watermark_lag_s'))}"
    )
    lines.append(
        f"  pressure {last('flow_pressure', 0.0):>5.2f} "
        f"{_spark(series('flow_pressure'))}   "
        f"shed/s {last('serve_shed_per_s', 0.0):>6.1f}   "
        f"timeouts/s {last('serve_timeouts_per_s', 0.0):>5.1f}"
    )
    metrics = tl.get("metrics") or []
    stages = sorted(m for m in metrics if m.startswith("stage_p99_s:"))
    if stages:
        lines.append("  p99 by stage:")
        for m in stages[:8]:
            lines.append(
                f"    {m.split(':', 1)[1]:<28} {last(m, 0.0) * 1e3:>8.1f} ms "
                f"{_spark(series(m))}"
            )
    phases = sorted(m for m in metrics if m.startswith("phase_ms:"))
    if phases:
        split = ", ".join(
            f"{m.split(':', 1)[1]}={last(m, 0.0):.0f}ms" for m in phases[:8]
        )
        lines.append(f"  tick split: {split}")
    health = status.get("health") or {}
    doors = (health.get("doors") or {}) if isinstance(health, dict) else {}
    alerts = health.get("alerts") if isinstance(health, dict) else None
    active = (alerts or {}).get("active") if isinstance(alerts, dict) else None
    state = health.get("door") or health.get("state")
    lines.append(
        f"  doors: {doors or state or 'n/a'}   "
        f"alerts: {[a.get('alert') for a in active] if active else 'none'}"
    )
    top = (status.get("bottleneck") or {}).get("top")
    if top:
        lines.append(
            f"  bound by: {top.get('cause')} (score {top.get('score')}) — "
            f"{top.get('verdict')}"
        )
        lines.append(f"  knob: {top.get('knob')}")
    else:
        lines.append("  bound by: (no bottleneck — idle or warming up)")
    return "\n".join(lines)


@cli.command()
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
@click.option("--proc", type=str, default=None, help="process id, or 'pod' for the merged rollup")
@click.option("--refresh", type=float, default=1.0, help="seconds between frames")
@click.option("--once", is_flag=True, default=False, help="print one frame and exit (no ANSI redraw)")
def top(host, port, proc, refresh, once):
    """Live terminal view of a RUNNING pipeline: qps, p99 by stage, watermark
    lag, backlog, pod pressure, per-phase tick split, doors/alerts and the
    current bottleneck verdict — read purely from the monitoring server's
    ``/timeline`` + ``/status`` endpoints."""
    import time as _t

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    qs = f"?proc={proc}" if proc else ""
    prev_lines = 0
    while True:
        status = _fetch_json(host, port, "/status")
        tl = _fetch_json(host, port, f"/timeline{qs}")
        if not tl.get("enabled", False):
            raise click.ClickException(
                "timeline plane is off on the target (PATHWAY_TIMELINE=off)"
            )
        frame = render_top(status, tl)
        if once:
            click.echo(frame)
            return
        if prev_lines:
            # redraw in place: cursor up + clear to end (LiveDashboard idiom)
            sys.stdout.write(f"\x1b[{prev_lines}F\x1b[J")
        sys.stdout.write(frame + "\n")
        sys.stdout.flush()
        prev_lines = frame.count("\n") + 1
        _t.sleep(max(0.1, refresh))


@cli.group()
def timeline() -> None:
    """Inspect spilled timeline segment directories."""


@timeline.command("diff")
@click.argument("dir_a", type=click.Path(exists=True, file_okay=False))
@click.argument("dir_b", type=click.Path(exists=True, file_okay=False))
@click.option(
    "--prefix",
    "prefixes",
    multiple=True,
    default=("phase_ms:", "stage_p99_s:"),
    show_default=True,
    help="metric prefixes to compare (repeatable)",
)
@click.option("--limit", type=int, default=12, help="rows to print")
def timeline_diff(dir_a, dir_b, prefixes, limit):
    """Cross-run comparison of two timeline segment directories: per-phase /
    per-stage mean cost in run A vs run B, worst regression first — names the
    PHASE that regressed, not just the number. Exits non-zero when B has no
    comparable series."""
    from pathway_tpu.observability.timeline import diff_summary, read_segments

    points_a = read_segments(dir_a)
    points_b = read_segments(dir_b)
    rows = diff_summary(points_a, points_b, prefixes=tuple(prefixes))
    if not rows:
        raise click.ClickException(
            f"no comparable series under {prefixes} in both directories "
            f"({len(points_a)} vs {len(points_b)} points read)"
        )
    click.echo(f"{'metric':<40} {'A':>12} {'B':>12} {'Δ%':>8}")
    for r in rows[: max(1, limit)]:
        click.echo(
            f"{r['metric']:<40} {r['a']:>12.4f} {r['b']:>12.4f} "
            f"{r['regression_pct']:>+8.1f}"
        )
    worst = rows[0]
    click.echo(
        f"worst regression: {worst['metric']} "
        f"({worst['regression_pct']:+.1f}% vs run A)"
    )


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("--record-path", type=str, default="./record", help="recorded persistence root")
@click.option(
    "--mode",
    type=click.Choice(["batch", "speedrun", "realtime"]),
    default="speedrun",
    help="replay pacing: batch/speedrun = as fast as possible, realtime = original pacing",
)
@click.option("-t", "--threads", type=int, default=1)
@click.option("-n", "--processes", type=int, default=1)
@click.option(
    "--continue-after-replay/--no-continue-after-replay",
    default=False,
    help="after replaying the recording, keep consuming live sources",
)
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def replay(record_path, mode, threads, processes, continue_after_replay, program):
    """Re-run PROGRAM against inputs recorded by `spawn --record`."""
    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(threads)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_PERSISTENT_STORAGE"] = record_path
    env["PATHWAY_REPLAY_MODE"] = mode
    env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "1" if continue_after_replay else "0"
    sys.exit(_spawn_processes(env, processes, program))


def main() -> None:
    cli()


if __name__ == "__main__":
    main()
