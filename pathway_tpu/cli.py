"""``pathway_tpu`` command line — spawn / spawn-from-env / replay.

Role of the reference CLI (``python/pathway/cli.py:53-113,167,253``): ``spawn``
forks N processes of a user program with the ``PATHWAY_THREADS / PATHWAY_PROCESSES /
PATHWAY_PROCESS_ID / PATHWAY_FIRST_PORT`` env contract consumed by
``parallel/cluster.py``; ``replay`` re-runs a program against a recorded
persistence log. Usage::

    python -m pathway_tpu spawn --threads 2 --processes 2 python script.py
    python -m pathway_tpu replay --record-path ./rec --mode speedrun python script.py
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys

import click

from pathway_tpu.internals.config import get_pathway_config


def _spawn_processes(env_base: dict[str, str], processes: int, args: tuple[str, ...]) -> int:
    """Fork one subprocess per process id; forward SIGINT/SIGTERM; return the
    first non-zero exit code (killing the rest), else 0."""
    if not args:
        raise click.UsageError("no program given (e.g. `spawn -t 2 python script.py`)")
    procs: list[subprocess.Popen] = []
    for pid in range(processes):
        env = dict(env_base, PATHWAY_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(list(args), env=env))

    def forward(signum, frame):
        for p in procs:
            if p.poll() is None:
                p.send_signal(signum)

    old_int = signal.signal(signal.SIGINT, forward)
    old_term = signal.signal(signal.SIGTERM, forward)
    try:
        code = 0
        for p in procs:
            rc = p.wait()
            if rc != 0 and code == 0:
                code = rc
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
        return code
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)


@click.group()
def cli() -> None:
    """pathway_tpu — TPU-native streaming dataflow framework."""


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=1, help="workers per process")
@click.option("-n", "--processes", type=int, default=1, help="number of processes")
@click.option("--first-port", type=int, default=None, help="base TCP port for the cluster plane")
@click.option("--record", is_flag=True, default=False, help="record inputs for later replay")
@click.option("--record-path", type=str, default="./record", help="where recorded inputs live")
@click.option(
    "--trace",
    type=click.Choice(["off", "on"]),
    default=None,
    help="live span pipeline (PATHWAY_TRACE)",
)
@click.option(
    "--trace-sample", type=float, default=None, help="tick head-sampling rate (0, 1]"
)
@click.option(
    "--trace-file",
    type=str,
    default=None,
    help="rotating OTLP-JSON live sink (per-process .pN suffix)",
)
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn(threads, processes, first_port, record, record_path, trace, trace_sample, trace_file, program):
    """Run PROGRAM across THREADS×PROCESSES workers on this host."""
    import uuid

    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(threads)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_FIRST_PORT"] = str(
        first_port if first_port is not None else get_pathway_config().first_port
    )
    # one run id per launch: every process derives the SAME trace id from it,
    # so per-process tick spans (live + offline exports) stitch into one trace
    env.setdefault("PATHWAY_RUN_ID", uuid.uuid4().hex)
    if trace is not None:
        env["PATHWAY_TRACE"] = trace
    if trace_sample is not None:
        env["PATHWAY_TRACE_SAMPLE"] = str(trace_sample)
    if trace_file is not None:
        env["PATHWAY_TRACE_LIVE_FILE"] = trace_file
    if record:
        env["PATHWAY_PERSISTENT_STORAGE"] = record_path
        env["PATHWAY_RECORD"] = "1"
    sys.exit(_spawn_processes(env, processes, program))


@cli.command(context_settings={"ignore_unknown_options": True})
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def spawn_from_env(program):
    """Like spawn, but topology comes from the current PATHWAY_* environment."""
    import uuid

    cfg = get_pathway_config()
    env = cfg.spawn_env(0)
    env.setdefault("PATHWAY_RUN_ID", uuid.uuid4().hex)  # shared trace id
    sys.exit(_spawn_processes(env, cfg.processes, program))


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("-t", "--threads", type=int, default=None, help="workers per process")
@click.option("-n", "--processes", type=int, default=None, help="number of processes")
@click.option("--first-port", type=int, default=None, help="base TCP port for the cluster plane")
@click.option("--max-restarts", type=int, default=None, help="restart budget on failure")
@click.option("--backoff", type=float, default=None, help="base restart backoff seconds")
@click.option("--log-dir", type=str, default=None, help="capture child output per attempt")
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def supervise(threads, processes, first_port, max_restarts, backoff, log_dir, program):
    """Run PROGRAM under the resilience Supervisor: spawn the cluster, detect
    a failed process, relaunch from the last committed checkpoint epoch with
    bounded exponential backoff (``resilience.Supervisor``)."""
    from pathway_tpu.resilience import Supervisor, SupervisorGaveUp

    if not program:
        raise click.UsageError("no program given (e.g. `supervise -n 2 python script.py`)")
    try:
        result = Supervisor(
            list(program),
            threads=threads,
            processes=processes,
            first_port=first_port,
            max_restarts=max_restarts,
            backoff_s=backoff,
            log_dir=log_dir,
        ).run()
    except SupervisorGaveUp as e:
        raise click.ClickException(str(e)) from e
    if result.restarts:
        click.echo(f"pathway_tpu supervise: recovered after {result.restarts} restart(s)")
    sys.exit(0)


@cli.command()
@click.option("--to", "target", type=int, required=True, help="target process count")
@click.option(
    "--storage",
    type=str,
    default=None,
    help="persistence root holding the cluster's membership table "
    "(default PATHWAY_PERSISTENT_STORAGE)",
)
@click.option("--host", type=str, default=None, help="instead of the storage path, hit a RUNNING coordinator's monitoring server")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
def scale(target, storage, host, port):
    """Request a live rescale of a running elastic cluster to TARGET
    processes (``PATHWAY_ELASTIC=manual`` or ``auto``). Default transport is
    the persistence backend: the request lands in ``elastic/scale_request``
    and the coordinator adopts it on its next tick-continuation barrier; the
    pod then quiesces to one final committed checkpoint epoch and its
    Supervisor relaunches it at the new shape, state resharded by key range.
    With ``--host``, the request goes to the coordinator's monitoring server
    ``/scale`` endpoint instead (no filesystem access needed)."""
    if target < 1:
        raise click.UsageError(f"--to must be >= 1, got {target}")
    if host is not None:
        import json as _json
        import urllib.request

        if port is None:
            port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
        url = f"http://{host}:{port}/scale?to={target}"
        try:
            body = urllib.request.urlopen(url, timeout=5).read().decode()
        except OSError as e:
            raise click.ClickException(
                f"cannot reach monitoring server at {host}:{port}: {e} "
                "(is the pipeline running with with_http_server=True?)"
            ) from e
        doc = _json.loads(body)
        click.echo(_json.dumps(doc, indent=2))
        if doc.get("ok") is False:
            raise click.ClickException(doc.get("error", "scale request failed"))
        return
    storage = storage or get_pathway_config().persistent_storage
    if not storage:
        raise click.UsageError(
            "no persistence root: pass --storage or set PATHWAY_PERSISTENT_STORAGE "
            "(or use --host to reach a running coordinator)"
        )
    from pathway_tpu.elastic import write_scale_request
    from pathway_tpu.persistence.backends import FileBackend

    req = write_scale_request(FileBackend(storage), target, source="cli")
    click.echo(
        f"scale request to {target} process(es) recorded at {storage!r} "
        f"(requested_unix {req['requested_unix']:.3f}); the coordinator "
        "adopts it within a tick"
    )


@cli.command()
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
@click.option("--ticks", type=int, default=None, help="capture window length in ticks")
@click.option(
    "--dir",
    "out_dir",
    type=str,
    default=None,
    help="capture output directory (default PATHWAY_PROFILE_DIR of the target run)",
)
@click.option("--status", is_flag=True, default=False, help="report the current window instead of arming one")
def profile(host, port, ticks, out_dir, status):
    """Arm a live ``jax.profiler`` capture window on a RUNNING pipeline via
    its monitoring server's ``/profile`` endpoint (view the result in
    TensorBoard/XProf)."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    qs = {}
    if not status:
        qs["ticks"] = str(ticks if ticks is not None else get_pathway_config().profile_ticks)
        if out_dir:
            qs["dir"] = out_dir
    url = f"http://{host}:{port}/profile"
    if qs:
        url += "?" + urllib.parse.urlencode(qs)
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "profile request failed"))


@cli.command()
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
@click.option("--sink", type=str, default=None, help="sink label, e.g. subscribe:7 (omit to list sinks)")
@click.option("--key", type=str, default=None, help="output row key (decimal or 0x-hex)")
def explain(host, port, sink, key):
    """Ask a RUNNING pipeline why a sink row exists: walk the operator graph
    backward through the lineage rings (``/explain`` endpoint) and print the
    contributing input rows, operator path, and originating trace span ids.
    Requires ``PATHWAY_AUDIT`` on (the default) and ``with_http_server=True``."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    qs = {}
    if sink:
        qs["sink"] = sink
    if key is not None:
        qs["key"] = key
    url = f"http://{host}:{port}/explain"
    if qs:
        url += "?" + urllib.parse.urlencode(qs)
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "explain request failed"))


@cli.command()
@click.argument("request_id", required=False)
@click.option("--host", type=str, default="127.0.0.1", help="monitoring server host")
@click.option(
    "--port",
    type=int,
    default=None,
    help="monitoring server port (default PATHWAY_MONITORING_HTTP_PORT, 20000)",
)
def trace(request_id, host, port):
    """Print one request's end-to-end flight path: per-stage latency
    decomposition + OTLP spans, from the request-trace plane's kept ring
    (``/request`` endpoint). Omit REQUEST_ID to list kept trace ids and the
    in-flight request table. The id is the ``X-Pathway-Request-Id`` response
    header the REST front door stamps on every admitted request."""
    import json as _json
    import urllib.parse
    import urllib.request

    if port is None:
        port = int(os.environ.get("PATHWAY_MONITORING_HTTP_PORT", "20000"))
    url = f"http://{host}:{port}/request"
    if request_id:
        url += "?" + urllib.parse.urlencode({"id": request_id})
    try:
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    except OSError as e:
        raise click.ClickException(
            f"cannot reach monitoring server at {host}:{port}: {e} "
            "(is the pipeline running with with_http_server=True?)"
        ) from e
    doc = _json.loads(body)
    click.echo(_json.dumps(doc, indent=2))
    if doc.get("ok") is False:
        raise click.ClickException(doc.get("error", "trace request failed"))


@cli.command(context_settings={"ignore_unknown_options": True})
@click.option("--record-path", type=str, default="./record", help="recorded persistence root")
@click.option(
    "--mode",
    type=click.Choice(["batch", "speedrun", "realtime"]),
    default="speedrun",
    help="replay pacing: batch/speedrun = as fast as possible, realtime = original pacing",
)
@click.option("-t", "--threads", type=int, default=1)
@click.option("-n", "--processes", type=int, default=1)
@click.option(
    "--continue-after-replay/--no-continue-after-replay",
    default=False,
    help="after replaying the recording, keep consuming live sources",
)
@click.argument("program", nargs=-1, type=click.UNPROCESSED)
def replay(record_path, mode, threads, processes, continue_after_replay, program):
    """Re-run PROGRAM against inputs recorded by `spawn --record`."""
    env = dict(os.environ)
    env["PATHWAY_THREADS"] = str(threads)
    env["PATHWAY_PROCESSES"] = str(processes)
    env["PATHWAY_REPLAY_STORAGE"] = record_path
    env["PATHWAY_PERSISTENT_STORAGE"] = record_path
    env["PATHWAY_REPLAY_MODE"] = mode
    env["PATHWAY_CONTINUE_AFTER_REPLAY"] = "1" if continue_after_replay else "0"
    sys.exit(_spawn_processes(env, processes, program))


def main() -> None:
    cli()


if __name__ == "__main__":
    main()
