"""Fault injection for recovery testing (``PATHWAY_FAULT_PLAN``).

The reference tests fault tolerance by killing a worker subprocess mid-run
(``integration_tests/wordcount`` recovery harness, SURVEY §5.4). This module
makes that reproducible and scriptable: a :class:`FaultPlan` names exact
injection points the runtimes consult on their hot paths, so both the recovery
test-suite and users chaos-testing a deployment can drive the SAME machinery.

Plan syntax (``;``-separated steps, each ``action:key=val,key=val``)::

    kill:proc=1,tick=40              # SIGKILL process 1 at the start of tick 40
    drop_poll:proc=0,tick=3,count=2  # drop connector polls for 2 ticks from t=3
    delay_barrier:proc=0,tick=4,ms=250,count=1  # delay 1 barrier call >=t4
    flip_diff:proc=0,tick=3          # negate one polled row's diff sign at t>=3
    drop_retract:proc=0,tick=3       # drop one polled retraction row at t>=3
    kill_point:point=delivery_staged  # SIGKILL at the Nth (count=) pass of a
                                      # named code point (faults.at_point)

Semantics:

- ``kill`` fires when the process's run loop reaches EXACTLY ``tick`` (ticks
  are sequential integers, so the match is deterministic) and SIGKILLs the
  process — no atexit, no flush: the hard-crash case.
- ``drop_poll`` suppresses connector polling for ``count`` consecutive ticks
  starting at ``tick`` (events buffer upstream; the pipeline sees a stalled
  source, the latency/recovery path a burst).
- ``delay_barrier`` sleeps ``ms`` before the next ``count`` barrier
  participations at or after ``tick`` (simulates a slow/hung peer without
  killing it — the heartbeat-timeout detection path).
- ``kill_point`` SIGKILLs at a NAMED code point instead of a tick boundary —
  subsystems bracket their crash windows with ``faults.at_point("<name>")``
  (e.g. the delivery plane's ``delivery_staged`` between the durable output
  stage and the manifest commit). ``count=N`` fires on the Nth pass.
- ``flip_diff`` / ``drop_retract`` are **data-plane corruptions** for testing
  the audit plane (``PATHWAY_AUDIT``) end-to-end: applied to freshly polled
  input blocks AFTER the connector/upsert machinery but BEFORE the audit
  monitors and the engine see them — exactly where a real corruption bug
  would live. ``flip_diff`` negates one row's diff sign (an insert becomes an
  unmatched retraction); ``drop_retract`` removes one retraction row (its
  insert stays live downstream forever). Each fires ``count`` times (default
  1), at the first eligible poll at or after ``tick`` — ``drop_retract``
  keeps scanning later ticks until a retraction actually appears.

``proc`` omitted means "any process". Every fired fault records a
``resilience.fault_*`` telemetry event (except ``kill``, which can only
print to stderr before dying).
"""

from __future__ import annotations

import os
import signal
import sys
import time as _time
from dataclasses import dataclass, field


@dataclass
class FaultSpec:
    action: str  # kill | drop_poll | delay_barrier | kill_point | ...
    proc: int | None = None  # None = any process
    tick: int = 0
    count: int = 1
    ms: float = 0.0
    point: str = ""  # kill_point: the named code point to fire at
    remaining: int = field(default=-1, repr=False)  # -1 = init from count

    def __post_init__(self) -> None:
        if self.remaining < 0:
            self.remaining = self.count

    def matches_proc(self, proc: int) -> bool:
        return self.proc is None or self.proc == proc


_ACTIONS = (
    "kill",
    "drop_poll",
    "delay_barrier",
    "flip_diff",
    "drop_retract",
    "kill_point",
)


class FaultPlan:
    """A parsed list of :class:`FaultSpec` steps with firing state."""

    def __init__(self, specs: list[FaultSpec] | None = None):
        self.specs = list(specs or [])

    # -- construction ---------------------------------------------------------
    @classmethod
    def parse(cls, text: str | None) -> "FaultPlan":
        specs: list[FaultSpec] = []
        for step in (text or "").split(";"):
            step = step.strip()
            if not step:
                continue
            action, _, kvs = step.partition(":")
            action = action.strip()
            if action not in _ACTIONS:
                raise ValueError(
                    f"unknown fault action {action!r} (expected one of {_ACTIONS})"
                )
            kwargs: dict = {}
            for kv in kvs.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                k = k.strip()
                if k == "proc":
                    kwargs["proc"] = int(v)
                elif k == "tick":
                    kwargs["tick"] = int(v)
                elif k == "count":
                    kwargs["count"] = int(v)
                elif k == "ms":
                    kwargs["ms"] = float(v)
                elif k == "point":
                    kwargs["point"] = v.strip()
                else:
                    raise ValueError(f"unknown fault option {k!r} in {step!r}")
            if action == "kill_point" and not kwargs.get("point"):
                raise ValueError(f"kill_point requires point= in {step!r}")
            specs.append(FaultSpec(action=action, **kwargs))
        return cls(specs)

    @classmethod
    def from_env(cls) -> "FaultPlan | None":
        text = os.environ.get("PATHWAY_FAULT_PLAN")
        if not text:
            return None
        return cls.parse(text)

    def to_env(self) -> str:
        """Serialize back to the ``PATHWAY_FAULT_PLAN`` syntax (for Supervisor
        child envs)."""
        steps = []
        for s in self.specs:
            kvs = []
            if s.proc is not None:
                kvs.append(f"proc={s.proc}")
            if s.action == "kill_point":
                kvs.append(f"point={s.point}")
            else:
                kvs.append(f"tick={s.tick}")
            if s.count != 1:
                kvs.append(f"count={s.count}")
            if s.ms:
                kvs.append(f"ms={s.ms:g}")
            steps.append(f"{s.action}:{','.join(kvs)}")
        return ";".join(steps)

    # -- firing ---------------------------------------------------------------
    def should_kill(self, proc: int, tick: int) -> bool:
        return any(
            s.action == "kill" and s.matches_proc(proc) and tick == s.tick
            for s in self.specs
        )

    def should_drop_poll(self, proc: int, tick: int) -> FaultSpec | None:
        for s in self.specs:
            if (
                s.action == "drop_poll"
                and s.matches_proc(proc)
                and s.tick <= tick < s.tick + s.count
            ):
                return s
        return None

    def take_corruption(self, proc: int, tick: int, has_retract: bool) -> FaultSpec | None:
        """One data-corruption firing for this (proc, tick), or None.
        ``drop_retract`` only fires when the polled block actually carries a
        retraction (it keeps waiting at later ticks otherwise)."""
        for s in self.specs:
            if (
                s.action in ("flip_diff", "drop_retract")
                and s.matches_proc(proc)
                and tick >= s.tick
                and s.remaining > 0
            ):
                if s.action == "drop_retract" and not has_retract:
                    continue
                s.remaining -= 1
                return s
        return None

    def take_point_kill(self, name: str, proc: int) -> FaultSpec | None:
        """One ``kill_point`` firing for this named code point (``count=N``
        fires on the Nth pass), or None."""
        for s in self.specs:
            if (
                s.action == "kill_point"
                and s.point == name
                and s.matches_proc(proc)
                and s.remaining > 0
            ):
                s.remaining -= 1
                if s.remaining == 0:
                    return s
        return None

    def take_barrier_delay(self, proc: int, tick: int) -> FaultSpec | None:
        for s in self.specs:
            if (
                s.action == "delay_barrier"
                and s.matches_proc(proc)
                and tick >= s.tick
                and s.remaining > 0
            ):
                s.remaining -= 1
                return s
        return None


# -- per-process active plan ---------------------------------------------------
# The run loops install the env plan at start (re-parsed each run, so firing
# state resets); hooks are no-ops when no plan is active (a single attribute
# read on the hot path). A manually installed plan (tests) takes precedence
# until cleared with install(None).

_active: FaultPlan | None = None
_manual = False


def install_from_env(force: bool = False) -> FaultPlan | None:
    global _active
    if _manual and not force:
        return _active
    _active = FaultPlan.from_env()
    return _active


def install(plan: FaultPlan | None) -> None:
    """Tests: install a plan directly (bypassing the env)."""
    global _active, _manual
    _active = plan
    _manual = plan is not None


def active() -> FaultPlan | None:
    return _active


def on_tick_start(proc: int, tick: int) -> bool:
    """Run-loop hook: may SIGKILL this process; returns True when connector
    polling should be dropped for this tick."""
    plan = _active
    if plan is None:
        return False
    if plan.should_kill(proc, tick):
        print(
            f"pathway_tpu fault injection: SIGKILL process {proc} at tick {tick}",
            file=sys.stderr,
            flush=True,
        )
        os.kill(os.getpid(), signal.SIGKILL)
    spec = plan.should_drop_poll(proc, tick)
    if spec is not None:
        from pathway_tpu.internals.telemetry import record_event

        record_event("resilience.fault_drop_poll", proc=proc, tick=tick)
        return True
    return False


def at_point(name: str, proc: int | None = None) -> None:
    """Named-crash-window hook: SIGKILLs this process when the active plan has
    a matching ``kill_point`` step. Subsystems call this to bracket windows
    that tick-addressed kills cannot hit deterministically (e.g. between the
    delivery plane's durable stage and the manifest commit). No plan = one
    attribute read."""
    plan = _active
    if plan is None:
        return
    if proc is None:
        proc = int(os.environ.get("PATHWAY_PROCESS_ID", "0") or 0)
    spec = plan.take_point_kill(name, proc)
    if spec is not None:
        print(
            f"pathway_tpu fault injection: SIGKILL process {proc} "
            f"at point {name!r}",
            file=sys.stderr,
            flush=True,
        )
        os.kill(os.getpid(), signal.SIGKILL)


def corrupt_polled(proc: int, tick: int, batches: list) -> list:
    """Data-corruption hook on freshly polled input blocks (every runtime's
    poll loop): applies at most one ``flip_diff`` / ``drop_retract`` firing.
    No plan (the overwhelmingly common case) is one attribute read."""
    plan = _active
    if plan is None or not batches:
        return batches
    has_retract = any(
        b is not None and len(b) and bool((b.diffs < 0).any()) for b in batches
    )
    spec = plan.take_corruption(proc, tick, has_retract)
    if spec is None:
        return batches
    import numpy as _np

    from pathway_tpu.internals.telemetry import record_event

    out = []
    pending = spec.action
    for b in batches:
        if pending is None or b is None or not len(b):
            out.append(b)
            continue
        if pending == "flip_diff":
            diffs = b.diffs.copy()
            diffs[0] = -diffs[0]
            b = type(b)(b.keys, diffs, b.data, b.time)
            record_event(
                "resilience.fault_flip_diff", proc=proc, tick=tick,
                key=int(b.keys[0]),
            )
            pending = None
        elif pending == "drop_retract":
            rets = _np.flatnonzero(b.diffs < 0)
            if len(rets):
                key = int(b.keys[rets[0]])
                keep = _np.ones(len(b), dtype=bool)
                keep[rets[0]] = False
                b = b.take(_np.flatnonzero(keep))
                record_event(
                    "resilience.fault_drop_retract", proc=proc, tick=tick, key=key
                )
                pending = None
        out.append(b)
    return out


def before_barrier(proc: int, tick: int) -> None:
    """Barrier hook: may delay this process's barrier participation."""
    plan = _active
    if plan is None:
        return
    spec = plan.take_barrier_delay(proc, tick)
    if spec is not None and spec.ms > 0:
        from pathway_tpu.internals.telemetry import record_event

        record_event(
            "resilience.fault_delay_barrier", proc=proc, tick=tick, ms=spec.ms
        )
        _time.sleep(spec.ms / 1000.0)
