"""Resilience: coordinated checkpoints, failure detection, supervised restart.

The cluster-plane fault-tolerance subsystem (reference: SURVEY §5.3–5.4 —
worker panic → ``OtherWorkerError``, recovery = restart + persistence replay,
fault injection by killing a subprocess mid-run). Three coupled pieces:

- **Coordinated checkpoint epochs** (``persistence/snapshots.py``): every
  cluster process persists its own input-log/operator shards; process 0
  commits a global epoch manifest only after a barrier proves every process's
  shards durable. :func:`last_committed_epoch` reads the newest fully-committed
  epoch — the restart point.
- **Failure detection** (:mod:`.heartbeat`): peer heartbeats to process 0 turn
  a dead or wedged peer into a structured
  :class:`~pathway_tpu.internals.errors.OtherWorkerError` (which process, what
  tick) well within ``barrier_timeout``.
- **Supervised restart + fault injection** (:mod:`.supervisor`, :mod:`.faults`):
  :class:`Supervisor` runs the cluster as child processes and relaunches from
  the last committed epoch with bounded exponential backoff;
  :class:`FaultPlan` (``PATHWAY_FAULT_PLAN``) injects kills / dropped polls /
  delayed barriers for recovery tests and chaos drills.

Consistency on restart is at-least-once input replay (the reference's OSS
tier); sinks with exactly-once hooks (``fs.write``) rewind to the epoch cut so
final outputs match an uninterrupted run byte for byte.
"""

from __future__ import annotations

from typing import Any

from pathway_tpu.internals.errors import OtherWorkerError
from pathway_tpu.resilience.faults import FaultPlan, FaultSpec
from pathway_tpu.resilience.heartbeat import HeartbeatClient, HeartbeatMonitor
from pathway_tpu.resilience.supervisor import (
    Supervisor,
    SupervisorGaveUp,
    SupervisorResult,
    supervise,
)


def last_committed_epoch(backend_or_config: Any) -> dict | None:
    """The newest fully-committed checkpoint epoch in a persistence backend
    (``persistence.Backend`` / ``persistence.Config`` / raw ``KVBackend``) —
    ``{"epoch", "tick", "input_offsets", "opsnap_gen", "acks", ...}`` or None
    when nothing has committed yet."""
    from pathway_tpu.persistence.snapshots import read_epoch_manifest

    return read_epoch_manifest(backend_or_config)


__all__ = [
    "FaultPlan",
    "FaultSpec",
    "HeartbeatClient",
    "HeartbeatMonitor",
    "OtherWorkerError",
    "Supervisor",
    "SupervisorGaveUp",
    "SupervisorResult",
    "last_committed_epoch",
    "supervise",
]
