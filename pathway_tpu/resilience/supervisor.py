"""Supervised cluster restart: run the pipeline as child processes, relaunch
on failure from the last committed checkpoint epoch.

The reference's recovery story (SURVEY §5.3–5.4) is "worker dies → restart the
cluster → persistence replays to the last finalized time". The
:class:`Supervisor` is that restart loop as a first-class object: it owns the
child processes (one per ``PATHWAY_PROCESS_ID``, the same env contract as
``python -m pathway_tpu spawn``), detects any child failing (non-zero exit or
death by signal), tears the survivors down, waits an exponential backoff, and
relaunches the whole cluster. Recovery state lives entirely in the persistence
backend — a relaunched cluster finds the newest fully-committed epoch
(``persistence/snapshots.py`` epoch manifest) and resumes from it, so the
supervisor itself is stateless across its own restarts.

Fault injection composes: set ``PATHWAY_FAULT_PLAN`` (see ``faults.py``) in the
supervisor env and the injected kill exercises exactly this path. By default
the plan is dropped from child envs after the first failure so a "kill at tick
N" fault doesn't re-fire forever on every relaunch.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from pathway_tpu.internals.config import get_pathway_config
from pathway_tpu.internals.telemetry import record_event


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted; ``attempts`` holds per-attempt info."""

    def __init__(self, message: str, attempts: list[dict]):
        super().__init__(message)
        self.attempts = attempts


@dataclass
class SupervisorResult:
    restarts: int
    attempts: list[dict] = field(default_factory=list)
    log_paths: list[str] = field(default_factory=list)


class Supervisor:
    """Run ``program`` as a ``processes``-wide cluster with bounded restarts.

    Parameters mirror the CLI spawn contract; unset values come from the
    ``PATHWAY_*`` environment (``PathwayConfig``). ``log_dir`` captures each
    child's combined stdout/stderr to ``attempt<k>-p<pid>.log`` (otherwise
    children inherit the supervisor's streams). ``on_restart(attempt, codes)``
    runs after a failed attempt is torn down and before the backoff sleep —
    tests use it to snapshot output files at the crash point.
    """

    def __init__(
        self,
        program: Sequence[str],
        *,
        processes: int | None = None,
        threads: int | None = None,
        first_port: int | None = None,
        max_restarts: int | None = None,
        backoff_s: float | None = None,
        backoff_max_s: float = 30.0,
        env: dict[str, str] | None = None,
        log_dir: str | None = None,
        clear_fault_plan_after_failure: bool = True,
        poll_interval: float = 0.05,
        term_grace_s: float = 5.0,
        on_restart: Callable[[int, list[int | None]], Any] | None = None,
    ):
        cfg = get_pathway_config()
        self.program = list(program)
        if not self.program:
            raise ValueError("Supervisor needs a program argv")
        self.processes = processes if processes is not None else cfg.processes
        self.threads = threads if threads is not None else cfg.threads
        self.first_port = first_port if first_port is not None else cfg.first_port
        self.max_restarts = (
            max_restarts if max_restarts is not None else cfg.supervisor_max_restarts
        )
        self.backoff_s = backoff_s if backoff_s is not None else cfg.supervisor_backoff_s
        self.backoff_max_s = backoff_max_s
        self.env = dict(env) if env is not None else dict(os.environ)
        self.log_dir = log_dir
        self.clear_fault_plan_after_failure = clear_fault_plan_after_failure
        self.poll_interval = poll_interval
        self.term_grace_s = term_grace_s
        self.on_restart = on_restart
        self.restarts = 0
        self.attempts: list[dict] = []

    # -- internals ------------------------------------------------------------
    def _child_env(self, pid: int, attempt: int) -> dict[str, str]:
        env = dict(self.env)
        env["PATHWAY_THREADS"] = str(self.threads)
        env["PATHWAY_PROCESSES"] = str(self.processes)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        env["PATHWAY_FIRST_PORT"] = str(self.first_port)
        env["PATHWAY_SUPERVISOR_ATTEMPT"] = str(attempt)
        return env

    def _launch(self, attempt: int) -> tuple[list[subprocess.Popen], list[str]]:
        procs: list[subprocess.Popen] = []
        logs: list[str] = []
        for pid in range(self.processes):
            out: Any = None
            if self.log_dir is not None:
                os.makedirs(self.log_dir, exist_ok=True)
                path = os.path.join(self.log_dir, f"attempt{attempt}-p{pid}.log")
                logs.append(path)
                out = open(path, "w")
            try:
                procs.append(
                    subprocess.Popen(
                        self.program,
                        env=self._child_env(pid, attempt),
                        stdout=out,
                        stderr=subprocess.STDOUT if out is not None else None,
                    )
                )
            finally:
                if out is not None:
                    out.close()  # the child holds its own fd
        return procs, logs

    def _wait_attempt(
        self, procs: list[subprocess.Popen]
    ) -> tuple[list[int | None], list[int]]:
        """Block until all children exit cleanly or any fails; on failure,
        terminate the survivors (TERM, grace, KILL). Returns (final exit
        codes, processes that failed ON THEIR OWN) — the failed set is
        captured BEFORE the teardown, so survivors the supervisor itself
        SIGTERMs are not misreported as the cause."""
        while True:
            codes = [p.poll() for p in procs]
            failed = [i for i, c in enumerate(codes) if c not in (None, 0)]
            if failed:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = _time.monotonic() + self.term_grace_s
                for p in procs:
                    if p.poll() is None:
                        timeout = max(0.0, deadline - _time.monotonic())
                        try:
                            p.wait(timeout=timeout)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait()
                return [p.returncode for p in procs], failed
            if all(c == 0 for c in codes):
                return codes, []
            _time.sleep(self.poll_interval)

    # -- public ---------------------------------------------------------------
    def run(self) -> SupervisorResult:
        all_logs: list[str] = []
        attempt = 0
        while True:
            t0_ns = _time.time_ns()
            procs, logs = self._launch(attempt)
            all_logs.extend(logs)
            codes, failed = self._wait_attempt(procs)
            info = {
                "attempt": attempt,
                "exit_codes": codes,
                "failed_processes": failed,
                "start_ns": t0_ns,
                "end_ns": _time.time_ns(),
            }
            self.attempts.append(info)
            if not failed:
                self._export_trace()
                return SupervisorResult(
                    restarts=self.restarts, attempts=self.attempts, log_paths=all_logs
                )
            record_event(
                "resilience.restart",
                attempt=attempt,
                failed_process=failed[0],
                exit_code=int(codes[failed[0]] or 0),
                restarts_so_far=self.restarts,
            )
            # flight-recorder post-mortem (device plane): the supervisor is
            # the surviving authority on WHICH process failed and when —
            # dumped before the relaunch overwrites the evidence
            from pathway_tpu.observability import device as _dev_prof

            _dev_prof.flight_note(
                "supervisor_restart",
                attempt=attempt,
                failed=failed,
                exit_codes=[c for c in codes],
            )
            _dev_prof.flight_dump(
                "supervisor_restart",
                extra={
                    "attempt": attempt,
                    "failed_processes": failed,
                    "exit_codes": codes,
                    "restarts_so_far": self.restarts,
                },
            )
            if attempt >= self.max_restarts:
                self._export_trace()
                raise SupervisorGaveUp(
                    f"cluster failed {attempt + 1} time(s) "
                    f"(processes {failed} exited {[codes[i] for i in failed]}); "
                    f"restart budget of {self.max_restarts} exhausted",
                    self.attempts,
                )
            if self.clear_fault_plan_after_failure:
                self.env.pop("PATHWAY_FAULT_PLAN", None)
            if self.on_restart is not None:
                self.on_restart(attempt, codes)
            delay = min(self.backoff_s * (2**attempt), self.backoff_max_s)
            if delay > 0:
                _time.sleep(delay)
            self.restarts += 1
            attempt += 1

    def _export_trace(self) -> None:
        """One span per attempt in an OTLP/JSON doc next to the run traces."""
        from pathway_tpu.internals import telemetry as _telemetry

        path = _telemetry.trace_file()
        if not path or not self.attempts:
            return
        try:
            spans = [
                (
                    "supervisor.attempt",
                    a["start_ns"],
                    a["end_ns"],
                    {
                        "pathway.supervisor.attempt": a["attempt"],
                        "pathway.supervisor.failed": bool(a["failed_processes"]),
                        "pathway.supervisor.exit_codes": str(a["exit_codes"]),
                    },
                )
                for a in self.attempts
            ]
            _telemetry.export_spans(
                f"{path}.supervisor", spans, root_name="pathway.supervise"
            )
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "supervisor trace export failed", exc_info=True
            )


def supervise(program: Sequence[str], **kwargs: Any) -> SupervisorResult:
    """Convenience wrapper: ``resilience.supervise([sys.executable, "p.py"])``."""
    return Supervisor(program, **kwargs).run()


def main(argv: Sequence[str] | None = None) -> int:  # pragma: no cover - CLI glue
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        Supervisor(argv).run()
        return 0
    except SupervisorGaveUp as e:
        print(f"pathway_tpu supervisor: {e}", file=sys.stderr)
        return 1
