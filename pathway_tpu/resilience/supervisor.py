"""Supervised cluster restart: run the pipeline as child processes, relaunch
on failure from the last committed checkpoint epoch.

The reference's recovery story (SURVEY §5.3–5.4) is "worker dies → restart the
cluster → persistence replays to the last finalized time". The
:class:`Supervisor` is that restart loop as a first-class object: it owns the
child processes (one per ``PATHWAY_PROCESS_ID``, the same env contract as
``python -m pathway_tpu spawn``), detects any child failing (non-zero exit or
death by signal), tears the survivors down, waits an exponential backoff, and
relaunches the whole cluster. Recovery state lives entirely in the persistence
backend — a relaunched cluster finds the newest fully-committed epoch
(``persistence/snapshots.py`` epoch manifest) and resumes from it, so the
supervisor itself is stateless across its own restarts.

Fault injection composes: set ``PATHWAY_FAULT_PLAN`` (see ``faults.py``) in the
supervisor env and the injected kill exercises exactly this path. By default
the plan is dropped from child envs after the first failure so a "kill at tick
N" fault doesn't re-fire forever on every relaunch.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time as _time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from pathway_tpu.internals.config import get_pathway_config
from pathway_tpu.internals.telemetry import record_event


class SupervisorGaveUp(RuntimeError):
    """The restart budget is exhausted; ``attempts`` holds per-attempt info."""

    def __init__(self, message: str, attempts: list[dict]):
        super().__init__(message)
        self.attempts = attempts


#: exit status of a coordinated elastic rescale (``elastic.RESCALE_EXIT_CODE``
#: — duplicated here so the supervisor has no import-order coupling with the
#: plane it relaunches; an assertion in tests pins the two together)
RESCALE_EXIT_CODE = 75


@dataclass
class SupervisorResult:
    restarts: int
    attempts: list[dict] = field(default_factory=list)
    log_paths: list[str] = field(default_factory=list)
    rescales: int = 0


class Supervisor:
    """Run ``program`` as a ``processes``-wide cluster with bounded restarts.

    Parameters mirror the CLI spawn contract; unset values come from the
    ``PATHWAY_*`` environment (``PathwayConfig``). ``log_dir`` captures each
    child's combined stdout/stderr to ``attempt<k>-p<pid>.log`` (otherwise
    children inherit the supervisor's streams). ``on_restart(attempt, codes)``
    runs after a failed attempt is torn down and before the backoff sleep —
    tests use it to snapshot output files at the crash point.
    """

    def __init__(
        self,
        program: Sequence[str],
        *,
        processes: int | None = None,
        threads: int | None = None,
        first_port: int | None = None,
        max_restarts: int | None = None,
        backoff_s: float | None = None,
        backoff_max_s: float = 30.0,
        env: dict[str, str] | None = None,
        log_dir: str | None = None,
        clear_fault_plan_after_failure: bool = True,
        poll_interval: float = 0.05,
        term_grace_s: float = 5.0,
        on_restart: Callable[[int, list[int | None]], Any] | None = None,
        storage: str | None = None,
        on_rescale: Callable[[int, int], Any] | None = None,
    ):
        cfg = get_pathway_config()
        self.program = list(program)
        if not self.program:
            raise ValueError("Supervisor needs a program argv")
        self.processes = processes if processes is not None else cfg.processes
        self.threads = threads if threads is not None else cfg.threads
        self.first_port = first_port if first_port is not None else cfg.first_port
        self.max_restarts = (
            max_restarts if max_restarts is not None else cfg.supervisor_max_restarts
        )
        self.backoff_s = backoff_s if backoff_s is not None else cfg.supervisor_backoff_s
        self.backoff_max_s = backoff_max_s
        self.env = dict(env) if env is not None else dict(os.environ)
        self.log_dir = log_dir
        self.clear_fault_plan_after_failure = clear_fault_plan_after_failure
        self.poll_interval = poll_interval
        self.term_grace_s = term_grace_s
        self.on_restart = on_restart
        #: persistence root holding the elastic membership table (defaults to
        #: the child env's PATHWAY_PERSISTENT_STORAGE) — read when an attempt
        #: exits with the rescale status to learn the new process count
        self.storage = storage if storage is not None else self.env.get(
            "PATHWAY_PERSISTENT_STORAGE"
        )
        self.on_rescale = on_rescale
        self.restarts = 0
        self.rescales = 0
        self.attempts: list[dict] = []

    # -- internals ------------------------------------------------------------
    def _child_env(self, pid: int, attempt: int) -> dict[str, str]:
        env = dict(self.env)
        env["PATHWAY_THREADS"] = str(self.threads)
        env["PATHWAY_PROCESSES"] = str(self.processes)
        env["PATHWAY_PROCESS_ID"] = str(pid)
        env["PATHWAY_FIRST_PORT"] = str(self.first_port)
        env["PATHWAY_SUPERVISOR_ATTEMPT"] = str(attempt)
        return env

    def _launch(self, attempt: int) -> tuple[list[subprocess.Popen], list[str]]:
        procs: list[subprocess.Popen] = []
        logs: list[str] = []
        for pid in range(self.processes):
            out: Any = None
            if self.log_dir is not None:
                os.makedirs(self.log_dir, exist_ok=True)
                path = os.path.join(self.log_dir, f"attempt{attempt}-p{pid}.log")
                logs.append(path)
                out = open(path, "w")
            try:
                procs.append(
                    subprocess.Popen(
                        self.program,
                        env=self._child_env(pid, attempt),
                        stdout=out,
                        stderr=subprocess.STDOUT if out is not None else None,
                    )
                )
            finally:
                if out is not None:
                    out.close()  # the child holds its own fd
        return procs, logs

    def _wait_attempt(
        self, procs: list[subprocess.Popen]
    ) -> tuple[list[int | None], list[int]]:
        """Block until all children exit cleanly or any fails; on failure,
        terminate the survivors (TERM, grace, KILL). Returns (final exit
        codes, processes that failed ON THEIR OWN) — the failed set is
        captured BEFORE the teardown, so survivors the supervisor itself
        SIGTERMs are not misreported as the cause.

        ``RESCALE_EXIT_CODE`` is not a failure: it is the coordinated
        elastic-rescale status every process adopts at the same barrier, so
        the loop simply waits for the stragglers (they are finishing the same
        quiesce) and returns clean."""
        while True:
            codes = [p.poll() for p in procs]
            failed = [
                i for i, c in enumerate(codes) if c not in (None, 0, RESCALE_EXIT_CODE)
            ]
            if failed:
                for p in procs:
                    if p.poll() is None:
                        p.terminate()
                deadline = _time.monotonic() + self.term_grace_s
                for p in procs:
                    if p.poll() is None:
                        timeout = max(0.0, deadline - _time.monotonic())
                        try:
                            p.wait(timeout=timeout)
                        except subprocess.TimeoutExpired:
                            p.kill()
                            p.wait()
                return [p.returncode for p in procs], failed
            if all(c is not None for c in codes):
                return codes, []
            _time.sleep(self.poll_interval)

    # -- public ---------------------------------------------------------------
    def run(self) -> SupervisorResult:
        all_logs: list[str] = []
        attempt = 0
        while True:
            t0_ns = _time.time_ns()
            procs, logs = self._launch(attempt)
            all_logs.extend(logs)
            codes, failed = self._wait_attempt(procs)
            rescale = not failed and any(c == RESCALE_EXIT_CODE for c in codes)
            info = {
                "attempt": attempt,
                "exit_codes": codes,
                "failed_processes": failed,
                "rescale": rescale,
                "start_ns": t0_ns,
                "end_ns": _time.time_ns(),
            }
            self.attempts.append(info)
            if rescale:
                # coordinated elastic rescale: the pod quiesced to a committed
                # epoch and published a new membership — relaunch at the new
                # shape immediately, spending neither restart budget nor
                # backoff (nothing failed)
                new_processes = self._rescale_target()
                record_event(
                    "elastic.rescale",
                    attempt=attempt,
                    from_processes=self.processes,
                    to_processes=new_processes,
                    rescales_so_far=self.rescales,
                )
                if self.on_rescale is not None:
                    self.on_rescale(self.processes, new_processes)
                self.processes = new_processes
                self.rescales += 1
                attempt += 1
                continue
            if not failed:
                self._export_trace()
                return SupervisorResult(
                    restarts=self.restarts,
                    attempts=self.attempts,
                    log_paths=all_logs,
                    rescales=self.rescales,
                )
            record_event(
                "resilience.restart",
                attempt=attempt,
                failed_process=failed[0],
                exit_code=int(codes[failed[0]] or 0),
                restarts_so_far=self.restarts,
            )
            # flight-recorder post-mortem (device plane): the supervisor is
            # the surviving authority on WHICH process failed and when —
            # dumped before the relaunch overwrites the evidence
            from pathway_tpu.observability import device as _dev_prof

            _dev_prof.flight_note(
                "supervisor_restart",
                attempt=attempt,
                failed=failed,
                exit_codes=[c for c in codes],
            )
            _dev_prof.flight_dump(
                "supervisor_restart",
                extra={
                    "attempt": attempt,
                    "failed_processes": failed,
                    "exit_codes": codes,
                    "restarts_so_far": self.restarts,
                },
            )
            # rescale attempts spend no restart budget: only FAILED attempts
            # count against it
            failures = sum(1 for a in self.attempts if a["failed_processes"])
            if failures - 1 >= self.max_restarts:
                self._export_trace()
                raise SupervisorGaveUp(
                    f"cluster failed {failures} time(s) "
                    f"(processes {failed} exited {[codes[i] for i in failed]}); "
                    f"restart budget of {self.max_restarts} exhausted",
                    self.attempts,
                )
            if self.clear_fault_plan_after_failure:
                self.env.pop("PATHWAY_FAULT_PLAN", None)
            if self.on_restart is not None:
                self.on_restart(attempt, codes)
            delay = min(self.backoff_s * (2**attempt), self.backoff_max_s)
            if delay > 0:
                _time.sleep(delay)
            self.restarts += 1
            attempt += 1

    def _rescale_target(self) -> int:
        """New process count from the committed membership table (the
        coordinator published it before exiting with the rescale status).
        ``storage`` may be a filesystem path (the PATHWAY_PERSISTENT_STORAGE
        default), a ``persistence.Backend`` config (S3 and friends), or a raw
        ``KVBackend``."""
        if self.storage is None or self.storage == "":
            raise SupervisorGaveUp(
                "cluster exited with the elastic rescale status but the "
                "supervisor has no persistence root to read the membership "
                "table from; pass storage= (path, persistence.Backend, or "
                "KVBackend) or set PATHWAY_PERSISTENT_STORAGE in the child "
                "environment",
                self.attempts,
            )
        from pathway_tpu.elastic import read_membership
        from pathway_tpu.persistence.backends import (
            FileBackend,
            KVBackend,
            backend_from_config,
        )

        if isinstance(self.storage, KVBackend):
            backend = self.storage
        elif isinstance(self.storage, str):
            backend = FileBackend(self.storage)
        else:
            backend = backend_from_config(self.storage)
        m = read_membership(backend)
        if m is None:
            raise SupervisorGaveUp(
                f"cluster exited with the elastic rescale status but "
                f"{self.storage!r} holds no membership table — the "
                "coordinator died between the decision and the commit; "
                "relaunch at the previous shape manually",
                self.attempts,
            )
        return m.processes

    def _export_trace(self) -> None:
        """One span per attempt in an OTLP/JSON doc next to the run traces."""
        from pathway_tpu.internals import telemetry as _telemetry

        path = _telemetry.trace_file()
        if not path or not self.attempts:
            return
        try:
            spans = [
                (
                    "supervisor.attempt",
                    a["start_ns"],
                    a["end_ns"],
                    {
                        "pathway.supervisor.attempt": a["attempt"],
                        "pathway.supervisor.failed": bool(a["failed_processes"]),
                        "pathway.supervisor.exit_codes": str(a["exit_codes"]),
                    },
                )
                for a in self.attempts
            ]
            _telemetry.export_spans(
                f"{path}.supervisor", spans, root_name="pathway.supervise"
            )
        except Exception:
            import logging

            logging.getLogger(__name__).warning(
                "supervisor trace export failed", exc_info=True
            )


def supervise(program: Sequence[str], **kwargs: Any) -> SupervisorResult:
    """Convenience wrapper: ``resilience.supervise([sys.executable, "p.py"])``."""
    return Supervisor(program, **kwargs).run()


def main(argv: Sequence[str] | None = None) -> int:  # pragma: no cover - CLI glue
    argv = list(sys.argv[1:] if argv is None else argv)
    try:
        Supervisor(argv).run()
        return 0
    except SupervisorGaveUp as e:
        print(f"pathway_tpu supervisor: {e}", file=sys.stderr)
        return 1
