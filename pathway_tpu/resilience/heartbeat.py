"""Cluster failure detection: heartbeats on the control plane.

The cluster's barrier protocol (``parallel/cluster.py``) only learns about a
dead peer when a barrier read times out after the full ``barrier_timeout`` —
and then it can't say WHICH peer died. This module adds the reference's
worker-liveness signal (SURVEY §5.3: a dead worker surfaces as
``OtherWorkerError`` naming the failure) as a dedicated heartbeat link per
peer:

- every non-coordinator process runs a :class:`HeartbeatClient` — a daemon
  thread holding one TCP connection to process 0 and sending
  ``("hb", pid, tick)`` every ``heartbeat_interval`` seconds;
- process 0 runs the :class:`HeartbeatMonitor` — it tracks per-peer last-seen
  times and last-known ticks, and answers :meth:`HeartbeatMonitor.dead_peer`
  for the barrier loops.

Detection is two-tier: a peer whose PROCESS dies (SIGKILL, OOM, crash) closes
its socket, so the monitor sees EOF within milliseconds; a peer that is alive
but wedged trips the ``heartbeat_timeout`` miss threshold. Either way the
barrier raises a structured ``OtherWorkerError(process_id=…, tick=…)`` instead
of a bare timeout. Clean shutdown sends ``("bye", pid)`` first so normal exits
never read as failures.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time as _time

from pathway_tpu.internals.telemetry import record_event


# same length-prefixed-pickle framing as the cluster plane (cluster.py), kept
# local so the detector has no import-order coupling with the runtime it guards
def _send(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(struct.pack("<Q", len(payload)) + payload)


def _recv(sock: socket.socket):
    buf = b""
    while len(buf) < 8:
        chunk = sock.recv(8 - len(buf))
        if not chunk:
            return None
        buf += chunk
    (n,) = struct.unpack("<Q", buf)
    payload = b""
    while len(payload) < n:
        chunk = sock.recv(n - len(payload))
        if not chunk:
            return None
        payload += chunk
    return pickle.loads(payload)


class _PeerState:
    __slots__ = ("last_seen", "tick", "eof", "clean", "summary")

    def __init__(self) -> None:
        self.last_seen = _time.monotonic()
        self.tick: int | None = None
        self.eof = False
        self.clean = False
        # latest telemetry summary shipped on the heartbeat (observability
        # plane: tick/watermark/backlog/sink-latency) — None until one arrives
        self.summary: dict | None = None


class HeartbeatMonitor:
    """Process 0's failure detector: accepts one heartbeat connection per peer."""

    def __init__(
        self,
        n_proc: int,
        port: int,
        host: str = "127.0.0.1",
        timeout: float = 10.0,
    ):
        self.n_proc = n_proc
        self.timeout = timeout
        self._lock = threading.Lock()
        self._peers: dict[int, _PeerState] = {}
        self._reported: set[int] = set()
        # elasticity hardening: peers retired by a drain decision — late
        # messages from them are dropped (warned once), never treated as
        # state or death
        self._retired: set[int] = set()
        self._stale_warned: set[int] = set()
        #: current membership version (elastic plane); summaries stamped with
        #: an older version are rejected as coming from before the reshard
        self.membership_version: int | None = None
        self._conns: list[socket.socket] = []
        self._closed = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(n_proc)
        self.port = self._listener.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(target=self._recv_loop, args=(conn,), daemon=True).start()

    def _recv_loop(self, conn: socket.socket) -> None:
        pid: int | None = None
        try:
            while True:
                msg = _recv(conn)
                if msg is None:
                    break  # EOF
                # 3-tuple = bare heartbeat; 4-tuple appends the telemetry
                # summary (observability plane) — both generations accepted
                kind, peer, tick = msg[0], msg[1], msg[2]
                summary = msg[3] if len(msg) > 3 else None
                if pid is None:
                    pid = int(peer)
                with self._lock:
                    if pid in self._retired:
                        # a just-retired peer's in-flight message: drop it
                        # with one structured warning — it must neither
                        # resurrect the peer state nor read as a death
                        if pid not in self._stale_warned:
                            self._stale_warned.add(pid)
                            record_event(
                                "elastic.stale_peer_message",
                                process_id=pid,
                                message_kind=str(kind),
                            )
                        return
                    st = self._peers.setdefault(pid, _PeerState())
                    st.last_seen = _time.monotonic()
                    if tick is not None:
                        st.tick = int(tick)
                    if summary is not None and self._summary_current(pid, summary):
                        st.summary = summary
                    if kind == "bye":
                        st.clean = True
                if kind == "bye":
                    break
        except Exception:
            pass  # a torn message counts as EOF below
        finally:
            if pid is not None:
                with self._lock:
                    st = self._peers.get(pid)
                    if st is not None and not st.clean:
                        st.eof = True
            try:
                conn.close()
            except OSError:
                pass

    def _summary_current(self, pid: int, summary: dict) -> bool:
        """Reject summaries stamped with a membership version older than the
        coordinator's (the sender predates the last reshard). Structured
        warning once per (peer, version); liveness is unaffected — only the
        telemetry payload is dropped. Caller holds ``self._lock``."""
        if self.membership_version is None or not isinstance(summary, dict):
            return True
        from pathway_tpu.elastic.membership import check_version

        return check_version(
            self.membership_version,
            summary.get("membership_version"),
            f"heartbeat:p{pid}",
        )

    def set_membership_version(self, version: int) -> None:
        with self._lock:
            self.membership_version = version

    def retire_peer(self, pid: int) -> None:
        """Elasticity drain: remove a peer from the failure detector and the
        flow merge — its clean (or abrupt) departure is expected, and its
        queue occupancy must stop scaling the pod's credit."""
        with self._lock:
            self._retired.add(pid)
            existed = self._peers.pop(pid, None) is not None
            self._reported.discard(pid)
        if existed:
            record_event("elastic.peer_retired", process_id=pid)

    def seen_peers(self) -> dict[int, int | None]:
        """pid → last-known tick, for every peer that ever connected."""
        with self._lock:
            return {pid: st.tick for pid, st in self._peers.items()}

    def peer_summaries(self) -> dict[int, dict | None]:
        """pid → latest telemetry summary shipped on that peer's heartbeats
        (None for peers that never sent one) — the coordinator's /status
        cluster section reads this."""
        with self._lock:
            return {pid: st.summary for pid, st in self._peers.items()}

    def peer_serving(self) -> dict[int, dict]:
        """pid → the per-route serving-counter block piggybacked on that
        peer's heartbeats (fabric front doors count their own ingress
        traffic). Empty entries are dropped; the serving /status rollup and
        the pod-wide shed/auth-failure totals read this."""
        with self._lock:
            out = {}
            for pid, st in self._peers.items():
                serving = (st.summary or {}).get("serving")
                if serving:
                    out[pid] = serving
            return out

    def peer_replica_index(self) -> dict[int, dict]:
        """pid → the replica-index health block (route → rows/lag/local/
        fallbacks/gaps/resyncs) piggybacked on that peer's heartbeats.
        Retired peers are gone from ``_peers``, so a drained door's stale
        lag never alarms the coordinator's rollup."""
        with self._lock:
            out = {}
            for pid, st in self._peers.items():
                ri = (st.summary or {}).get("replica_index")
                if ri:
                    out[pid] = ri
            return out

    def peer_flow(self) -> dict[int, dict]:
        """pid → the flow-plane credit/occupancy block piggybacked on that
        peer's heartbeats ({} until one arrives). The coordinator merges these
        into the pod-wide pressure it broadcasts on the tick barrier, which is
        what makes backpressure CLUSTER-wide: a peer whose ingest queues fill
        shrinks every process's effective credit. Drained peers (clean
        goodbye or retired by an elastic drain) drop out: a gone process's
        stale occupancy must not keep throttling the survivors."""
        with self._lock:
            return {
                pid: (st.summary or {}).get("flow") or {}
                for pid, st in self._peers.items()
                if not st.clean and not st.eof
            }

    def dead_peer(self) -> tuple[int, int | None, str] | None:
        """(pid, last_tick, reason) of a failed peer, else None. EOF beats a
        heartbeat miss (it is definitive); each miss is recorded once."""
        if self._closed:
            return None
        now = _time.monotonic()
        with self._lock:
            for pid, st in self._peers.items():
                if st.clean:
                    continue
                if st.eof:
                    return pid, st.tick, "disconnected"
            for pid, st in self._peers.items():
                if st.clean or st.eof:
                    continue
                if now - st.last_seen > self.timeout:
                    if pid not in self._reported:
                        self._reported.add(pid)
                        record_event(
                            "resilience.heartbeat_miss",
                            process_id=pid,
                            tick=st.tick if st.tick is not None else -1,
                            silent_s=round(now - st.last_seen, 3),
                        )
                    return pid, st.tick, "heartbeat-timeout"
        return None

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass


class HeartbeatClient:
    """A peer's side: one connection + a daemon sender thread. The runtime
    bumps ``self.tick`` each tick; ``coordinator_lost`` flips when sends start
    failing after a successful connect (process 0 is gone)."""

    def __init__(
        self,
        pid: int,
        port: int,
        interval: float,
        host: str = "127.0.0.1",
        connect_timeout: float = 30.0,
    ):
        self.pid = pid
        self.interval = interval
        self.tick = 0
        self.coordinator_lost = False
        # optional telemetry provider (observability plane): when set, each
        # heartbeat carries its summary so process 0 aggregates the cluster
        self.summary_fn = None
        self._closed = False
        self._sock: socket.socket | None = None
        self._host = host
        self._port = port
        self._connect_timeout = connect_timeout
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        deadline = _time.monotonic() + self._connect_timeout
        while not self._closed:
            try:
                self._sock = socket.create_connection(
                    (self._host, self._port), timeout=5
                )
                break
            except OSError:
                if _time.monotonic() > deadline:
                    return  # no monitor (e.g. heartbeats disabled on pid 0)
                _time.sleep(0.05)
        while not self._closed:
            summary = None
            fn = self.summary_fn
            if fn is not None:
                try:
                    summary = fn()
                except Exception:
                    summary = None  # telemetry must never kill the heartbeat
            try:
                _send(self._sock, ("hb", self.pid, self.tick, summary))
            except OSError:
                if not self._closed:
                    self.coordinator_lost = True
                    record_event(
                        "resilience.heartbeat_miss",
                        process_id=0,
                        tick=self.tick,
                        silent_s=0.0,
                    )
                return
            _time.sleep(self.interval)

    def goodbye(self) -> None:
        """Clean shutdown: tell the monitor this exit is intentional."""
        self._closed = True
        sock = self._sock
        if sock is not None:
            try:
                _send(sock, ("bye", self.pid, self.tick))
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
